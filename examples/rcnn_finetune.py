"""Two-stage detection fine-tune, R-CNN style (reference: example/rcnn
— RPN proposals + ROI pooling + per-ROI classification head). Tiny
TPU-native rendition with the classic fine-tune recipe: a frozen conv
backbone, a sampled fg/bg ROI set (jittered ground-truth boxes vs
low-IoU background boxes — the reference's fg/bg sampling rule), and a
trained ROIPooling->Dense head. The Proposal op (anchors + NMS via the
Pallas greedy-NMS kernel) runs end-to-end to produce region candidates
the trained head then scores, detection-style. Returns (held-out ROI
accuracy, positive rate).
"""
from __future__ import annotations

import argparse

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def _scenes(rs, n, size):
    """One bright square object per image; label = its box."""
    x = rs.rand(n, 1, size, size).astype('float32') * 0.15
    boxes = np.zeros((n, 4), 'float32')
    for i in range(n):
        s = rs.randint(size // 4, size // 2)
        r0, c0 = rs.randint(0, size - s, 2)
        x[i, 0, r0:r0 + s, c0:c0 + s] += 1.0
        boxes[i] = (c0, r0, c0 + s - 1, r0 + s - 1)
    return x, boxes


def _iou(rois, box):
    x1 = np.maximum(rois[:, 0], box[0])
    y1 = np.maximum(rois[:, 1], box[1])
    x2 = np.minimum(rois[:, 2], box[2])
    y2 = np.minimum(rois[:, 3], box[3])
    inter = np.clip(x2 - x1 + 1, 0, None) * np.clip(y2 - y1 + 1, 0, None)
    a1 = (rois[:, 2] - rois[:, 0] + 1) * (rois[:, 3] - rois[:, 1] + 1)
    a2 = (box[2] - box[0] + 1) * (box[3] - box[1] + 1)
    return inter / (a1 + a2 - inter + 1e-9)


def _sample_rois(rs, boxes, size, per_image=4):
    """fg = ground truth jittered by <=2px; bg = random low-IoU boxes
    (the reference's fg/bg ROI sampling, rcnn sample_rois)."""
    rois, labels = [], []
    for img, box in enumerate(boxes):
        for _ in range(per_image // 2):
            j = rs.randint(-2, 3, 4).astype('float32')
            fg = np.clip(box + j, 0, size - 1)
            rois.append([img, *fg])
            labels.append(1.0)
        made = 0
        while made < per_image - per_image // 2:
            s = rs.randint(size // 5, size // 2)
            c0, r0 = rs.randint(0, size - s, 2)
            bg = np.array([c0, r0, c0 + s - 1, r0 + s - 1], 'float32')
            if _iou(bg[None], box)[0] < 0.2:
                rois.append([img, *bg])
                labels.append(0.0)
                made += 1
    return (np.asarray(rois, 'float32'),
            np.asarray(labels, 'float32'))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=16)
    p.add_argument('--num-samples', type=int, default=16)
    p.add_argument('--size', type=int, default=32)
    p.add_argument('--lr', type=float, default=0.02)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.ndarray.ndarray import invoke

    np.random.seed(0)          # deterministic initializer draws
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    X, B = _scenes(rs, args.num_samples, args.size)
    stride = 4

    backbone = nn.HybridSequential()
    with backbone.name_scope():
        backbone.add(nn.Conv2D(8, 3, padding=1, activation='relu'),
                     nn.MaxPool2D(2),
                     nn.Conv2D(16, 3, padding=1, activation='relu'),
                     nn.MaxPool2D(2))
    backbone.initialize(mx.init.Xavier())

    head = nn.HybridSequential()
    with head.name_scope():
        head.add(nn.Dense(32, activation='relu'), nn.Dense(2))
    head.initialize(mx.init.Xavier())
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(head.collect_params(), 'adam',
                            {'learning_rate': args.lr})

    def pooled_feats(xb, rois_np):
        feats = backbone(nd.array(xb))
        return invoke('ROIPooling', [feats, nd.array(rois_np)],
                      dict(pooled_size=(3, 3),
                           spatial_scale=1.0 / stride))

    split = args.num_samples * 3 // 4
    train_rois, train_y = _sample_rois(rs, B[:split], args.size)
    test_rois, test_y = _sample_rois(rs, B[split:], args.size)
    test_rois[:, 0] += split

    for _ in range(args.epochs):
        pooled = pooled_feats(X, train_rois)
        with autograd.record():
            loss = L(head(pooled), nd.array(train_y))
        loss.backward()
        trainer.step(pooled.shape[0])

    pred = head(pooled_feats(X, test_rois)).asnumpy().argmax(axis=1)
    acc = float((pred == test_y).mean())

    # end-to-end RPN path: anchors + NMS propose candidate regions the
    # trained head scores (detection-style inference demo)
    feats = backbone(nd.array(X[split:split + 1]))
    fmap = feats.asnumpy()
    energy = np.abs(fmap).mean(axis=1, keepdims=True)
    n_anchor = 2                       # scales (2, 4) x ratios (1.0,)
    cls = np.concatenate([1 - energy] * n_anchor + [energy] * n_anchor,
                         axis=1).astype('float32')
    deltas = np.zeros((1, 4 * n_anchor) + fmap.shape[2:], 'float32')
    im_info = np.array([[args.size, args.size, 1.0]], 'float32')
    proposals = invoke('_contrib_Proposal',
                       [nd.array(cls), nd.array(deltas),
                        nd.array(im_info)],
                       dict(rpn_pre_nms_top_n=32, rpn_post_nms_top_n=4,
                            threshold=0.5, rpn_min_size=4,
                            scales=(2, 4), ratios=(1.0,),
                            feature_stride=stride))
    scored = invoke('ROIPooling', [feats, proposals],
                    dict(pooled_size=(3, 3),
                         spatial_scale=1.0 / stride))
    obj_scores = head(scored).asnumpy()
    assert obj_scores.shape == (4, 2)

    print('rcnn head accuracy %.3f (positives %.2f)'
          % (acc, test_y.mean()))
    return acc, float(test_y.mean())


if __name__ == '__main__':
    main()
