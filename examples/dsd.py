"""Dense-Sparse-Dense training (reference: example/dsd — train dense,
prune the smallest weights to a sparsity mask and retrain under the
mask, then release the mask and retrain dense at low LR; Han 2017).
Returns (dense accuracy, sparse-phase accuracy, final accuracy,
achieved sparsity).
"""
from __future__ import annotations

import argparse

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--phase-epochs', type=int, default=6)
    p.add_argument('--num-samples', type=int, default=512)
    p.add_argument('--sparsity', type=float, default=0.5)
    p.add_argument('--lr', type=float, default=3e-3)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn

    np.random.seed(0)
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    from examples.multi_task import synth_digits
    x_np, y_np = synth_digits(rs, args.num_samples)
    split = args.num_samples * 3 // 4
    xs, ys = nd.array(x_np), nd.array(y_np)

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Flatten(), nn.Dense(96, activation='relu'),
                nn.Dense(48, activation='relu'), nn.Dense(10))
    net.initialize(mx.init.Xavier())
    L_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def accuracy():
        pred = net(xs[split:]).asnumpy().argmax(1)
        return float((pred == y_np[split:]).mean())

    def train(epochs, lr, masks=None):
        trainer = gluon.Trainer(net.collect_params(), 'adam',
                                {'learning_rate': lr})
        for _ in range(epochs):
            for i in range(0, split, 64):
                xb, yb = xs[i:i + 64], ys[i:i + 64]
                with autograd.record():
                    loss = L_fn(net(xb), yb)
                loss.backward()
                trainer.step(xb.shape[0])
                if masks:
                    for param, mask in masks.items():
                        param.set_data(param.data() * mask)

    # phase 1: dense
    train(args.phase_epochs, args.lr)
    acc_dense = accuracy()

    # phase 2: prune smallest |w| per dense layer, retrain masked
    masks = {}
    for name, param in net.collect_params().items():
        if not name.endswith('weight'):
            continue
        w = param.data().asnumpy()
        thresh = np.quantile(np.abs(w), args.sparsity)
        masks[param] = nd.array((np.abs(w) > thresh).astype('float32'))
        param.set_data(param.data() * masks[param])
    train(args.phase_epochs, args.lr, masks)
    acc_sparse = accuracy()
    nnz = sum(float(m.asnumpy().sum()) for m in masks.values())
    tot = sum(float(m.size) for m in masks.values())
    sparsity = 1.0 - nnz / tot

    # phase 3: release the mask, retrain dense at lower LR
    train(args.phase_epochs, args.lr * 0.1)
    acc_final = accuracy()
    print('dsd accuracy dense %.3f sparse %.3f final %.3f '
          '(sparsity %.2f)' % (acc_dense, acc_sparse, acc_final,
                               sparsity))
    return acc_dense, acc_sparse, acc_final, sparsity


if __name__ == '__main__':
    main()
