"""Distributed data-parallel training (reference:
example/distributed_training — multi-worker training over the
launcher/kvstore contract). Run standalone it spawns its own two
workers through tools/launch (the reference's `launch.py -n 2`); as a
worker it joins the dist_sync kvstore, trains a shared linear model on
its data shard, and verifies all workers converge to the SAME params.
Returns (mse, max cross-worker param divergence).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def worker(result_path):
    # the distributed client must come up before any JAX backend does
    # (the launch contract; _dist_init fails loudly otherwise)
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import jax
    try:
        jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])
    except Exception:
        pass
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd

    kv = mx.kv.create('dist_sync')
    rank, nw = kv.rank, kv.num_workers
    rs = np.random.RandomState(0)
    w_true = rs.randn(8).astype('float32')
    per_worker = 128
    x_all = rs.randn(per_worker * nw, 8).astype('float32')
    y_all = x_all @ w_true
    shard = slice(rank * per_worker, (rank + 1) * per_worker)
    xs, ys = nd.array(x_all[shard]), nd.array(y_all[shard])

    w = nd.zeros((8,))
    w.attach_grad()
    gsum = nd.zeros((8,))
    kv.init('g', gsum)
    for _ in range(60):
        with autograd.record():
            loss = ((nd.dot(xs, w) - ys) ** 2).mean()
        loss.backward()
        # push local grads (the store holds their cross-worker SUM),
        # pull the reduced gradient, apply the identical update locally
        kv.push('g', w.grad)
        kv.pull('g', out=gsum)
        w[:] = w - (0.05 / nw) * gsum
    kv._barrier()
    mse = float(((nd.dot(xs, w) - ys) ** 2).mean().asscalar())
    with open('%s.%d' % (result_path, rank), 'w') as f:
        json.dump({'mse': mse, 'w': w.asnumpy().tolist()}, f)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--num-workers', type=int, default=2)
    p.add_argument('--worker', default=None, help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args.worker:
        worker(args.worker)
        return None

    from mxnet_tpu.tools.launch import launch_local
    result = os.path.join(tempfile.mkdtemp(prefix='dist_train_'), 'res')
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {'PYTHONPATH': os.pathsep.join(
        [root, os.environ.get('PYTHONPATH', '')]),
        'JAX_PLATFORMS': os.environ.get('JAX_PLATFORMS', 'cpu')}
    codes = launch_local(
        args.num_workers,
        [sys.executable, os.path.abspath(__file__), '--worker', result],
        env=env)
    assert codes == [0] * args.num_workers, codes
    reports = []
    for r in range(args.num_workers):
        with open('%s.%d' % (result, r)) as f:
            reports.append(json.load(f))
    ws = np.array([rep['w'] for rep in reports])
    divergence = float(np.abs(ws - ws[0]).max())
    mse = max(rep['mse'] for rep in reports)
    print('dist_train: %d workers, worst mse %.5f, param divergence '
          '%.2e' % (args.num_workers, mse, divergence))
    return mse, divergence


if __name__ == '__main__':
    main()
