"""Out-of-tree operator plugin (reference: plugin/ — external ops
compiled into the registry; docs/OP_PLUGINS.md). Writes a plugin
module to disk, loads it with mx.plugin.load, and trains a network
whose activation IS the plugin op — eager, hybridized, and through
the symbolic executor. Returns (accuracy, plugin op present in JSON).
"""
from __future__ import annotations

import argparse
import os
import tempfile
import textwrap

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np

PLUGIN_SRC = '''
import jax
import jax.numpy as jnp
from mxnet_tpu import plugin


@plugin.register_op('smooth_relu6', num_inputs=1)
def smooth_relu6(data, *, sharpness=4.0):
    """A softplus-smoothed relu6 — not in the built-in registry."""
    s = float(sharpness)
    soft = jax.nn.softplus(s * data) / s
    return 6.0 - jax.nn.softplus(s * (6.0 - soft)) / s
'''


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=8)
    p.add_argument('--num-samples', type=int, default=384)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn

    with tempfile.NamedTemporaryFile('w', suffix='.py',
                                     delete=False) as f:
        f.write(textwrap.dedent(PLUGIN_SRC))
        path = f.name
    try:
        mx.plugin.load(path)
    finally:
        os.unlink(path)

    np.random.seed(0)
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    from examples.multi_task import synth_digits
    x_np, y_np = synth_digits(rs, args.num_samples)
    split = args.num_samples * 3 // 4

    class Net(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.flat = nn.Flatten()
                self.fc1 = nn.Dense(64)
                self.fc2 = nn.Dense(10)

        def hybrid_forward(self, F, x):
            return self.fc2(F.smooth_relu6(self.fc1(self.flat(x))))

    net = Net()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), 'adam',
                       {'learning_rate': 3e-3})
    xs, ys = nd.array(x_np), nd.array(y_np)
    for _ in range(args.epochs):
        for i in range(0, split, 64):
            with autograd.record():
                loss = L(net(xs[i:i + 64]), ys[i:i + 64])
            loss.backward()
            tr.step(64)
    pred = net(xs[split:]).asnumpy().argmax(1)
    acc = float((pred == y_np[split:]).mean())

    # the plugin op also exists symbolically and serializes
    s = mx.sym.smooth_relu6(mx.sym.Variable('d'), sharpness=2.0)
    in_json = '"op": "smooth_relu6"' in s.tojson()
    print('plugin-op accuracy %.3f (in symbol JSON: %s)'
          % (acc, in_json))
    return acc, in_json


if __name__ == '__main__':
    main()
