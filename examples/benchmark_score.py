"""Inference throughput benchmark (reference:
example/image-classification/benchmark_score.py; published numbers
docs/faq/perf.md:167-193 — the BASELINE.md inference table).

Scores hybridized model-zoo networks with one jitted forward per batch,
fp32 and bf16, printing one JSON line per (model, dtype).
"""
import json
import time

# shared standalone-run bootstrap (repo root onto sys.path); when
# imported as examples.* the root is already importable and the
# script dir is not on sys.path, so gate on standalone execution
if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np

# published 1x V100 bs=128 numbers (BASELINE.md)
_V100 = {('resnet50_v1', 'float32'): 1233.15,
         ('resnet50_v1', 'bfloat16'): 2355.04,   # vs V100 fp16
         ('resnet152_v1', 'float32'): 511.79,
         ('inception_v3', 'float32'): 904.33}


def score(model_name, dtype, batch=128, image=224, iters=20):
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import model_zoo

    if model_name == 'inception_v3':
        image = 299
    net = getattr(model_zoo.vision, model_name)()
    net.initialize(mx.init.Xavier())
    if dtype != 'float32':
        net.cast(dtype)
    net.hybridize(static_alloc=True, static_shape=True)
    x = nd.array(np.random.uniform(-1, 1, (batch, 3, image, image)),
                 dtype=dtype)
    for _ in range(3):
        net(x)
    nd.waitall()
    t0 = time.perf_counter()
    for _ in range(iters):
        # block every call: identical async dispatches could otherwise be
        # coalesced by the backend, overstating throughput
        net(x).wait_to_read()
    dt = time.perf_counter() - t0
    img_s = batch * iters / dt
    base = _V100.get((model_name, dtype))
    print(json.dumps({
        'metric': '%s_%s_infer_img_per_sec' % (model_name, dtype),
        'value': round(img_s, 2), 'unit': 'img/s',
        'vs_baseline': round(img_s / base, 3) if base else None}))
    return img_s


def main(argv=None):
    import argparse
    import jax
    on_accel = jax.default_backend() != 'cpu'
    p = argparse.ArgumentParser()
    p.add_argument('--models', default=None,
                   help='comma list of model:dtype pairs (default: the '
                        'published four-config table)')
    p.add_argument('--batch', type=int, default=128 if on_accel else 4)
    p.add_argument('--image', type=int, default=224)
    p.add_argument('--iters', type=int, default=20 if on_accel else 2)
    args = p.parse_args(argv)
    if args.models:
        configs = []
        for m in args.models.split(','):
            name, _, dtype = m.partition(':')
            configs.append((name, dtype or 'float32'))
    else:
        configs = [('resnet50_v1', 'float32'),
                   ('resnet50_v1', 'bfloat16'),
                   ('resnet152_v1', 'float32'),
                   ('inception_v3', 'float32')]
    rates = []
    for model, dtype in configs:
        rates.append(score(model, dtype, batch=args.batch,
                           image=args.image, iters=args.iters))
    return rates


if __name__ == '__main__':
    main()
