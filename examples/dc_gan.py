"""DCGAN — adversarial training with two networks and two trainers
(reference: example/gluon/dc_gan/dcgan.py). Conv2DTranspose generator,
Conv2D discriminator, alternating D/G updates with SigmoidBCE loss.
Synthetic 16x16 "blob" images replace MNIST in zero-egress environments.
"""
from __future__ import annotations

import argparse

# shared standalone-run bootstrap (repo root onto sys.path); when
# imported as examples.* the root is already importable and the
# script dir is not on sys.path, so gate on standalone execution
if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def build_nets(nz=16, ngf=16, ndf=16):
    from mxnet_tpu.gluon import nn
    netG = nn.HybridSequential()
    with netG.name_scope():
        # nz x 1 x 1 -> 16 x 16
        netG.add(nn.Conv2DTranspose(ngf * 2, 4, 1, 0, use_bias=False),
                 nn.BatchNorm(), nn.Activation('relu'),
                 nn.Conv2DTranspose(ngf, 4, 2, 1, use_bias=False),
                 nn.BatchNorm(), nn.Activation('relu'),
                 nn.Conv2DTranspose(1, 4, 2, 1, use_bias=False),
                 nn.Activation('tanh'))
    netD = nn.HybridSequential()
    with netD.name_scope():
        netD.add(nn.Conv2D(ndf, 4, 2, 1, use_bias=False),
                 nn.LeakyReLU(0.2),
                 nn.Conv2D(ndf * 2, 4, 2, 1, use_bias=False),
                 nn.BatchNorm(), nn.LeakyReLU(0.2),
                 nn.Conv2D(1, 4, 1, 0, use_bias=False))
    return netG, netD


def real_batch(rs, batch):
    """Bright gaussian blobs on dark background, values in [-1, 1]."""
    xs = np.full((batch, 1, 16, 16), -0.9, dtype=np.float32)
    for i in range(batch):
        cy, cx = rs.randint(4, 12, size=2)
        yy, xx = np.mgrid[0:16, 0:16]
        blob = np.exp(-((yy - cy) ** 2 + (xx - cx) ** 2) / 8.0)
        xs[i, 0] = blob * 1.8 - 0.9
    return xs


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--batch-size', type=int, default=16)
    p.add_argument('--iters', type=int, default=30)
    p.add_argument('--nz', type=int, default=16)
    p.add_argument('--lr', type=float, default=2e-4)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    netG, netD = build_nets(args.nz)
    netG.initialize(mx.init.Normal(0.02))
    netD.initialize(mx.init.Normal(0.02))
    trainerG = gluon.Trainer(netG.collect_params(), 'adam',
                             {'learning_rate': args.lr, 'beta1': 0.5})
    trainerD = gluon.Trainer(netD.collect_params(), 'adam',
                             {'learning_rate': args.lr, 'beta1': 0.5})
    L = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    rs = np.random.RandomState(0)
    real_label = nd.ones((args.batch_size,))
    fake_label = nd.zeros((args.batch_size,))
    errD = errG = None
    for it in range(args.iters):
        data = nd.array(real_batch(rs, args.batch_size))
        noise = nd.array(rs.randn(args.batch_size, args.nz, 1, 1)
                         .astype(np.float32))
        # D step: maximize log D(x) + log(1 - D(G(z)))
        with autograd.record():
            out_real = netD(data).reshape((-1,))
            fake = netG(noise)
            out_fake = netD(fake.detach()).reshape((-1,))
            errD = L(out_real, real_label) + L(out_fake, fake_label)
        errD.backward()
        trainerD.step(args.batch_size)
        # G step: maximize log D(G(z))
        with autograd.record():
            out = netD(netG(noise)).reshape((-1,))
            errG = L(out, real_label)
        errG.backward()
        trainerG.step(args.batch_size)
        if it % 10 == 0:
            print('iter %d errD %.3f errG %.3f' %
                  (it, float(errD.mean().asscalar()),
                   float(errG.mean().asscalar())))
    d, g = float(errD.mean().asscalar()), float(errG.mean().asscalar())
    assert np.isfinite(d) and np.isfinite(g)
    return d, g


if __name__ == '__main__':
    main()
