"""Matrix factorization with model-parallel placement — the recommender
workload (reference: example/recommenders/ and
example/model-parallel/matrix_factorization/model.py:23-38, which
splits the two embedding tables across devices with AttrScope
ctx_group). TPU-native: the same split expressed as pjit sharding rules
over a device mesh — the embeddings shard over the 'mp' axis while the
batch rides 'dp'.
"""
from __future__ import annotations

import argparse

# shared standalone-run bootstrap (repo root onto sys.path); when
# imported as examples.* the root is already importable and the
# script dir is not on sys.path, so gate on standalone execution
if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--num-users', type=int, default=200)
    p.add_argument('--num-items', type=int, default=100)
    p.add_argument('--factors', type=int, default=16)
    p.add_argument('--batch-size', type=int, default=64)
    p.add_argument('--epochs', type=int, default=8)
    p.add_argument('--lr', type=float, default=0.05)
    p.add_argument('--mesh', action='store_true',
                   help='train the fused step over a dp x mp mesh')
    args = p.parse_args(argv)

    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn

    rs = np.random.RandomState(0)
    # low-rank ground truth ratings
    u_true = rs.randn(args.num_users, 4).astype(np.float32)
    i_true = rs.randn(args.num_items, 4).astype(np.float32)
    n = 4096
    users = rs.randint(0, args.num_users, n)
    items = rs.randint(0, args.num_items, n)
    ratings = (u_true[users] * i_true[items]).sum(1)

    class MF(nn.HybridBlock):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.user = nn.Embedding(args.num_users, args.factors)
                self.item = nn.Embedding(args.num_items, args.factors)

        def hybrid_forward(self, F, u, i):
            return (self.user(u) * self.item(i)).sum(axis=1)

    net = MF()
    net.initialize(mx.init.Normal(0.1))
    L = gluon.loss.L2Loss()

    ndev_all = len(jax.devices())
    if args.mesh and ndev_all >= 2 and ndev_all % 2 == 0:
        # model-parallel analog: embedding tables shard over 'mp'
        from jax.sharding import PartitionSpec as P
        from mxnet_tpu import parallel
        ndev = len(jax.devices())
        mesh = parallel.create_mesh({'dp': ndev // 2, 'tp': 2})
        # both embedding tables shard their vocab dim over 'tp' — the
        # ctx_group split of model.py:23-38, as sharding rules
        rules = parallel.ShardingRules(
            overrides={'embedding': P('tp', None)})
        pt = parallel.ParallelTrainer(net, L, 'adam',
                                      {'learning_rate': args.lr},
                                      mesh, rules=rules)
        step = lambda u, i, r: float(pt.step([u, i], [r]).asscalar())
    else:
        trainer = gluon.Trainer(net.collect_params(), 'adam',
                                {'learning_rate': args.lr})

        def step(u, i, r):
            with autograd.record():
                loss = L(net(u, i), r)
            loss.backward()
            trainer.step(u.shape[0])
            return float(loss.mean().asscalar())

    mse = None
    for epoch in range(args.epochs):
        order = rs.permutation(n)
        tot = cnt = 0
        for b in range(0, n, args.batch_size):
            idx = order[b:b + args.batch_size]
            tot += step(nd.array(users[idx]), nd.array(items[idx]),
                        nd.array(ratings[idx]))
            cnt += 1
        mse = tot / cnt
        print('epoch %d loss %.4f' % (epoch, mse))
    assert mse < 1.0, 'MF should fit the low-rank ratings'
    return mse


if __name__ == '__main__':
    main()
