"""Deep Q-Network with replay buffer and target network (reference:
example/reinforcement-learning/dqn — DQN over the Atari stack; here the
same algorithmic parts on the in-repo Balance environment so the smoke
is synthetic and egress-free).

The three DQN ingredients the reference exercises:
  * experience replay (uniform buffer, minibatch TD(0) targets),
  * a frozen target network synced every K steps,
  * epsilon-greedy behavior policy with linear decay.
The TD step is one hybridized forward per network + a Huber loss under
a single autograd.record scope — batch Q-learning maps to the MXU as a
pair of dense matmuls, no per-sample Python.
"""
from __future__ import annotations

import argparse

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


class Replay:
    def __init__(self, cap, rs):
        self.cap, self.rs = cap, rs
        self.data = []
        self.pos = 0

    def push(self, item):
        if len(self.data) < self.cap:
            self.data.append(item)
        else:
            self.data[self.pos] = item
        self.pos = (self.pos + 1) % self.cap

    def sample(self, n):
        idx = self.rs.randint(0, len(self.data), n)
        s, a, r, s2, done = zip(*(self.data[i] for i in idx))
        return (np.stack(s), np.asarray(a, np.int64),
                np.asarray(r, np.float32), np.stack(s2),
                np.asarray(done, np.float32))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--episodes', type=int, default=250)
    p.add_argument('--batch-size', type=int, default=64)
    p.add_argument('--gamma', type=float, default=0.99)
    p.add_argument('--lr', type=float, default=1e-3)
    p.add_argument('--sync-every', type=int, default=100)
    p.add_argument('--train-every', type=int, default=1)
    p.add_argument('--buffer', type=int, default=5000)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn
    from examples.actor_critic import Balance

    rs = np.random.RandomState(0)
    mx.random.seed(0)
    env = Balance(seed=0)

    def make_q():
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(64, activation='relu'),
                    nn.Dense(64, activation='relu'), nn.Dense(2))
        net.initialize(mx.init.Xavier())
        net.hybridize()
        return net

    q, target = make_q(), make_q()

    def sync():
        for (_, src), (_, dst) in zip(q.collect_params().items(),
                                      target.collect_params().items()):
            dst.set_data(src.data())

    q(nd.array(np.zeros((1, 4), np.float32)))
    target(nd.array(np.zeros((1, 4), np.float32)))
    sync()

    trainer = gluon.Trainer(q.collect_params(), 'adam',
                            {'learning_rate': args.lr})
    loss_fn = gluon.loss.HuberLoss()
    buf = Replay(args.buffer, rs)
    steps = 0
    returns = []
    for ep in range(args.episodes):
        s = env.reset()
        total = 0.0
        eps = max(0.05, 1.0 - ep / (0.6 * args.episodes))
        while True:
            if rs.rand() < eps:
                a = rs.randint(0, 2)
            else:
                qv = q(nd.array(s[None])).asnumpy()
                a = int(qv.argmax())
            s2, r, done = env.step(a)
            buf.push((s, a, r, s2, float(done)))
            total += r
            s = s2
            steps += 1
            if len(buf.data) >= args.batch_size and \
                    steps % args.train_every == 0:
                bs_, ba, br, bs2, bd = buf.sample(args.batch_size)
                q_next = target(nd.array(bs2)).asnumpy().max(1)
                y = br + args.gamma * q_next * (1.0 - bd)
                with autograd.record():
                    q_all = q(nd.array(bs_))
                    q_sel = nd.pick(q_all, nd.array(ba), axis=1)
                    loss = loss_fn(q_sel, nd.array(y))
                loss.backward()
                trainer.step(args.batch_size)
            if steps % args.sync_every == 0:
                sync()
            if done:
                break
        returns.append(total)
    early = float(np.mean(returns[:10]))
    late = float(np.mean(returns[-10:]))
    print('dqn return early %.1f -> late %.1f' % (early, late))
    return early, late


if __name__ == '__main__':
    main()
