"""Shared standalone-run bootstrap: put the repo root on sys.path so
`python examples/<script>.py` finds mxnet_tpu without touching
PYTHONPATH (the TPU plugin loads via the ambient PYTHONPATH's
sitecustomize — overriding it breaks backend registration). The
reference centralizes the same trick in
example/image-classification/common/find_mxnet.py.
"""
import os
import sys

_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _root not in sys.path:
    sys.path.insert(0, _root)
