"""Post-training int8 quantization walkthrough (reference:
example/quantization — quantize a trained fp32 model with calibration
and compare scores/speed). Trains a small conv net, quantizes it with
each calibration mode (naive / percentile / KL-entropy), reports the
accuracy drop, and times fp32 vs int8 inference on the current
backend. Returns dict with per-mode accuracy and the speedup.
"""
from __future__ import annotations

import argparse
import time

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=6)
    p.add_argument('--num-samples', type=int, default=512)
    p.add_argument('--bench-iters', type=int, default=20)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import nd

    np.random.seed(0)
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    from examples.multi_task import synth_digits
    x_np, y_np = synth_digits(rs, args.num_samples)
    split = args.num_samples * 3 // 4

    data = mx.sym.Variable('data')
    h = mx.sym.Convolution(data, kernel=(3, 3), num_filter=16,
                           pad=(1, 1), name='conv1')
    h = mx.sym.Activation(h, act_type='relu')
    h = mx.sym.Pooling(h, kernel=(2, 2), stride=(2, 2), pool_type='max')
    h = mx.sym.Flatten(h)
    h = mx.sym.FullyConnected(h, num_hidden=64, name='fc1')
    h = mx.sym.Activation(h, act_type='relu')
    h = mx.sym.FullyConnected(h, num_hidden=10, name='fc2')
    out = mx.sym.SoftmaxOutput(h, name='softmax')

    train = mx.io.NDArrayIter(x_np[:split], y_np[:split], batch_size=64,
                              shuffle=True)
    mod = mx.mod.Module(out, label_names=('softmax_label',))
    mod.fit(train, num_epoch=args.epochs,
            optimizer_params={'learning_rate': 0.05},
            initializer=mx.init.Xavier())
    arg_params, aux_params = mod.get_params()

    def score(sym, params, aux):
        n_eval = args.num_samples - split
        ex = sym.bind(mx.context.current_context(),
                      args=dict(params, data=nd.array(x_np[split:]),
                                softmax_label=nd.zeros((n_eval,))),
                      aux_states=dict(aux))
        outp = ex.forward()[0].asnumpy()
        return float((outp.argmax(1) == y_np[split:]).mean())

    fp32_acc = score(out, arg_params, aux_params)
    results = {'fp32': fp32_acc}
    calib = [nd.array(x_np[i:i + 64]) for i in range(0, split, 64)][:4]
    qmodels = {}
    for mode in ('naive', 'percentile', 'entropy'):
        qsym, qargs, qaux = mx.contrib.quantization.quantize_model(
            out, arg_params, aux_params, calib_data=calib,
            calib_mode=mode)
        results[mode] = score(qsym, qargs, qaux)
        qmodels[mode] = (qsym, qargs, qaux)

    # inference timing, fp32 vs int8 (entropy-calibrated)
    def bench(sym, params, aux):
        x = nd.array(x_np[:64])
        ex = sym.bind(mx.context.current_context(),
                      args=dict(params, data=x,
                                softmax_label=nd.zeros((64,))),
                      aux_states=dict(aux))
        ex.forward()[0].wait_to_read()
        t0 = time.perf_counter()
        for _ in range(args.bench_iters):
            o = ex.forward()[0]
        o.wait_to_read()
        return 64 * args.bench_iters / (time.perf_counter() - t0)

    fp32_ips = bench(out, arg_params, aux_params)
    q = qmodels['entropy']
    int8_ips = bench(*q)
    results['speedup'] = int8_ips / fp32_ips
    print('quantize_int8 acc fp32 %.3f naive %.3f percentile %.3f '
          'entropy %.3f | int8 %.0f img/s vs fp32 %.0f img/s '
          '(x%.2f)' % (results['fp32'], results['naive'],
                       results['percentile'], results['entropy'],
                       int8_ips, fp32_ips, results['speedup']))
    return results


if __name__ == '__main__':
    main()
