"""Sequence labeling with CTC, speech-style (reference: example/speech*
and example/ctc — acoustic-model stacks trained with warp-CTC). Tiny
TPU-native rendition: synthetic 'utterances' (each frame a noisy
one-hot of the symbol being 'spoken', stretched to variable durations)
-> BiLSTM over the fused RNN op (lax.scan) -> per-frame logits -> the
framework CTCLoss. Greedy CTC decode measures sequence accuracy.
Returns (label error rate, baseline error rate of an untrained net).
"""
from __future__ import annotations

import argparse

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def _utterances(rs, n, n_sym, T, L):
    """Each sample: L symbols, each held for a random duration, with
    noise — the classic toy CTC task."""
    x = np.zeros((n, T, n_sym + 2), 'float32')
    labels = np.zeros((n, L), 'float32')
    for i in range(n):
        # no immediate repeats: a repeated symbol needs an explicit
        # blank between its spans, which pure one-hot frames cannot cue
        syms = [rs.randint(1, n_sym + 1)]
        while len(syms) < L:
            nxt = rs.randint(1, n_sym + 1)
            if nxt != syms[-1]:
                syms.append(nxt)
        syms = np.asarray(syms)
        labels[i] = syms
        cuts = np.sort(rs.choice(np.arange(1, T), L - 1, replace=False))
        spans = np.split(np.arange(T), cuts)
        for sym, span in zip(syms, spans):
            x[i, span, sym] = 1.0
    x += rs.randn(n, T, n_sym + 2).astype('float32') * 0.3
    return x, labels


def _greedy_decode(logits, blank):
    """Collapse repeats then drop blanks (standard CTC decode)."""
    path = logits.argmax(axis=-1)
    out = []
    for row in path:
        seq, prev = [], -1
        for sym in row:
            if sym != prev and sym != blank:
                seq.append(int(sym))
            prev = sym
        out.append(seq)
    return out


def _edit_distance(a, b):
    """Levenshtein distance (the standard CTC label-error metric)."""
    dp = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        prev, dp[0] = dp[0], i
        for j, cb in enumerate(b, 1):
            prev, dp[j] = dp[j], min(dp[j] + 1, dp[j - 1] + 1,
                                     prev + (ca != cb))
    return dp[-1]


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=15)
    p.add_argument('--num-samples', type=int, default=160)
    p.add_argument('--symbols', type=int, default=5)
    p.add_argument('--frames', type=int, default=24)
    p.add_argument('--label-len', type=int, default=3)
    p.add_argument('--hidden', type=int, default=32)
    p.add_argument('--lr', type=float, default=0.02)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn, rnn

    np.random.seed(0)
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    n_sym = args.symbols
    vocab = n_sym + 2                 # symbols + silence + CTC blank
    blank = vocab - 1                 # CTCLoss uses blank_label='last'
    X, Y = _utterances(rs, args.num_samples, n_sym, args.frames,
                       args.label_len)

    class AcousticModel(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.encoder = rnn.LSTM(args.hidden, num_layers=1,
                                        bidirectional=True,
                                        layout='NTC')
                self.head = nn.Dense(vocab, flatten=False)

        def hybrid_forward(self, F, x):
            return self.head(self.encoder(x))   # (N, T, vocab)

    net = AcousticModel()
    net.initialize(mx.init.Xavier())
    ctc = gluon.loss.CTCLoss(layout='NTC', label_layout='NT')
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})

    xs, ys = nd.array(X), nd.array(Y)
    split = args.num_samples * 3 // 4

    def error_rate(lo, hi):
        """Label error rate: edit distance normalised by label length."""
        decoded = _greedy_decode(net(xs[lo:hi]).asnumpy(), blank)
        total = sum(_edit_distance(seq, [int(v) for v in want])
                    for seq, want in zip(decoded, Y[lo:hi]))
        return total / ((hi - lo) * args.label_len)

    baseline = error_rate(split, args.num_samples)   # untrained
    batch = 16
    for _ in range(args.epochs):
        for i in range(0, split, batch):
            xb, yb = xs[i:i + batch], ys[i:i + batch]
            with autograd.record():
                loss = ctc(net(xb), yb)
            loss.backward()
            trainer.step(xb.shape[0])

    ler = error_rate(split, args.num_samples)
    print('ctc label error rate %.3f (untrained baseline %.3f)'
          % (ler, baseline))
    return ler, baseline


if __name__ == '__main__':
    main()
