"""Named-entity recognition with a BiLSTM tagger (reference:
example/named_entity_recognition — sequence labeling over tokens).
Synthetic corpus: entity tokens are drawn from small dedicated
vocabulary ranges (PER/LOC), everything else is O; multi-token
entities tag B-/I- style. Returns (entity-token F1-ish recall,
tagging accuracy).
"""
from __future__ import annotations

import argparse

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np

TAGS = ['O', 'B-PER', 'I-PER', 'B-LOC', 'I-LOC']


def make_corpus(rs, n, vocab, seq_len):
    x = rs.randint(40, vocab, (n, seq_len))
    y = np.zeros((n, seq_len), np.int64)
    for i in range(n):
        for _ in range(rs.randint(1, 3)):
            kind = rs.randint(0, 2)          # 0=PER tokens 10-19, 1=LOC 20-29
            length = rs.randint(1, 3)
            start = rs.randint(0, seq_len - length)
            base = 10 if kind == 0 else 20
            x[i, start:start + length] = rs.randint(base, base + 10, length)
            y[i, start] = 1 + 2 * kind                     # B-*
            y[i, start + 1:start + length] = 2 + 2 * kind  # I-*
    return x, y


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=12)
    p.add_argument('--num-samples', type=int, default=512)
    p.add_argument('--vocab', type=int, default=120)
    p.add_argument('--seq-len', type=int, default=10)
    p.add_argument('--hidden', type=int, default=48)
    p.add_argument('--lr', type=float, default=5e-3)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn, rnn

    np.random.seed(0)
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    x_np, y_np = make_corpus(rs, args.num_samples, args.vocab,
                             args.seq_len)

    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Embedding(args.vocab, 24),
                rnn.LSTM(args.hidden, bidirectional=True, layout='NTC'),
                nn.Dense(len(TAGS), flatten=False))
    net.initialize(mx.init.Xavier())
    L_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})

    split = args.num_samples * 3 // 4
    xs, ys = nd.array(x_np), nd.array(y_np.astype('float32'))
    batch = 64
    for _ in range(args.epochs):
        for i in range(0, split, batch):
            xb, yb = xs[i:i + batch], ys[i:i + batch]
            with autograd.record():
                logits = net(xb)
                loss = L_fn(logits.reshape((-1, len(TAGS))),
                            yb.reshape((-1,)))
            loss.backward()
            trainer.step(xb.shape[0])

    pred = net(xs[split:]).asnumpy().argmax(-1)
    gold = y_np[split:]
    acc = float((pred == gold).mean())
    ent = gold > 0
    recall = float((pred[ent] == gold[ent]).mean()) if ent.any() else 0.0
    print('ner entity recall %.3f tagging accuracy %.3f' % (recall, acc))
    return recall, acc


if __name__ == '__main__':
    main()
