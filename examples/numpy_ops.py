"""Custom numpy-backed operator (reference: example/numpy-ops — a
softmax written in numpy through mx.operator.CustomOp, trained inside
a normal network). Demonstrates the host-callback escape hatch: the op
body is arbitrary numpy, the engine schedules it eagerly with fences,
and autograd consumes the hand-written backward. Returns accuracy.
"""
from __future__ import annotations

import argparse

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=8)
    p.add_argument('--num-samples', type=int, default=384)
    p.add_argument('--lr', type=float, default=0.1)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd

    class NumpySoftmaxCE(mx.operator.CustomOp):
        """Softmax + cross-entropy in pure numpy (reference
        example/numpy-ops/custom_softmax.py)."""

        def forward(self, is_train, req, in_data, out_data, aux):
            z = in_data[0].asnumpy()
            z = z - z.max(axis=1, keepdims=True)
            e = np.exp(z)
            self.assign(out_data[0], req[0],
                        mx.nd.array(e / e.sum(axis=1, keepdims=True)))

        def backward(self, req, out_grad, in_data, out_data, in_grad,
                     aux):
            # dL/dz for CE-with-softmax given labels in in_data[1]
            y = np.array(out_data[0].asnumpy())  # writable copy
            lab = in_data[1].asnumpy().astype(int)
            y[np.arange(len(lab)), lab] -= 1.0
            self.assign(in_grad[0], req[0], mx.nd.array(y / len(lab)))

    @mx.operator.register('numpy_softmax_ce')
    class NumpySoftmaxCEProp(mx.operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=False)

        def list_arguments(self):
            return ['data', 'label']

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, in_shapes, in_dtypes):
            return NumpySoftmaxCE()

    np.random.seed(0)
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    from examples.multi_task import synth_digits
    x_np, y_np = synth_digits(rs, args.num_samples)
    x_np = x_np.reshape(args.num_samples, -1)
    split = args.num_samples * 3 // 4

    w = nd.array(rs.randn(10, x_np.shape[1]).astype('float32') * 0.01)
    w.attach_grad()
    xs, ys = nd.array(x_np), nd.array(y_np)
    for _ in range(args.epochs):
        for i in range(0, split, 64):
            xb, yb = xs[i:i + 64], ys[i:i + 64]
            with autograd.record():
                logits = nd.dot(xb, w.T)
                probs = nd.Custom(logits, yb,
                                  op_type='numpy_softmax_ce')
                # the custom op handles the CE gradient itself
                # (need_top_grad=False); summing keeps a scalar head
                head = probs.sum()
            head.backward()
            w[:] = w - args.lr * w.grad
    pred = nd.dot(xs[split:], w.T).asnumpy().argmax(1)
    acc = float((pred == y_np[split:]).mean())
    print('numpy-ops custom softmax accuracy %.3f' % acc)
    return acc


if __name__ == '__main__':
    main()
