"""Multivariate time-series forecasting (reference:
example/multivariate_time_series — LSTNet: conv feature extraction
over a sliding window + recurrent layer + autoregressive highway).
Synthetic coupled-sinusoid system with noise; one-step-ahead
forecasting. Returns (model RMSE, persistence-baseline RMSE).
"""
from __future__ import annotations

import argparse

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def make_series(rs, steps, dims):
    t = np.arange(steps)[:, None]
    phases = rs.rand(1, dims) * 6.28
    freqs = 0.15 + 0.35 * rs.rand(1, dims)
    base = np.sin(freqs * t + phases)
    coupling = 0.4 * np.roll(base, 1, axis=1)
    return (base + coupling + 0.05 * rs.randn(steps, dims)) \
        .astype('float32')


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=20)
    p.add_argument('--steps', type=int, default=900)
    p.add_argument('--dims', type=int, default=6)
    p.add_argument('--window', type=int, default=24)
    p.add_argument('--lr', type=float, default=3e-3)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn, rnn

    np.random.seed(0)
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    series = make_series(rs, args.steps, args.dims)
    W = args.window
    xs_np = np.stack([series[i:i + W]
                      for i in range(len(series) - W)])
    ys_np = series[W:]
    split = int(len(xs_np) * 0.8)

    class LSTNetLite(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.conv = nn.Conv1D(24, 5, activation='relu')
                self.gru = rnn.GRU(32, layout='NTC')
                self.head = nn.Dense(args.dims)
                self.ar = nn.Dense(args.dims, use_bias=False)

        def hybrid_forward(self, F, x):          # (B, W, D)
            c = self.conv(x.transpose((0, 2, 1)))  # (B, F, W')
            h = self.gru(c.transpose((0, 2, 1)))   # (B, W', H)
            deep = self.head(h[:, -1, :])
            # autoregressive highway on the last observation
            return deep + self.ar(x[:, -1, :])

    net = LSTNetLite()
    net.initialize(mx.init.Xavier())
    L2 = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})

    xs, ys = nd.array(xs_np), nd.array(ys_np)
    batch = 64
    for _ in range(args.epochs):
        for i in range(0, split, batch):
            xb, yb = xs[i:i + batch], ys[i:i + batch]
            with autograd.record():
                loss = L2(net(xb), yb).mean()
            loss.backward()
            trainer.step(1)

    pred = net(xs[split:]).asnumpy()
    rmse = float(np.sqrt(((pred - ys_np[split:]) ** 2).mean()))
    persist = float(np.sqrt(
        ((xs_np[split:, -1, :] - ys_np[split:]) ** 2).mean()))
    print('time-series rmse %.4f (persistence baseline %.4f)'
          % (rmse, persist))
    return rmse, persist


if __name__ == '__main__':
    main()
