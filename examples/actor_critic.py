"""Actor-critic reinforcement learning — the RL capability workload
(reference: example/gluon/actor_critic.py; reinforcement-learning/).
A self-contained CartPole-style balance environment (pure numpy, no
gym) trained with one-step advantage actor-critic: policy head sampled
via the framework's sample_multinomial op, losses composed under one
autograd.record scope.
"""
from __future__ import annotations

import argparse

# shared standalone-run bootstrap (repo root onto sys.path); when
# imported as examples.* the root is already importable and the
# script dir is not on sys.path, so gate on standalone execution
if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


class Balance:
    """Minimal cart-pole: state (x, x', th, th'), actions {left, right};
    episode ends when |th| > 12deg or |x| > 2.4 or after 200 steps."""

    def __init__(self, seed=0):
        self.rs = np.random.RandomState(seed)

    def reset(self):
        self.s = self.rs.uniform(-0.05, 0.05, 4).astype(np.float32)
        self.t = 0
        return self.s.copy()

    def step(self, action):
        x, xd, th, thd = self.s
        force = 10.0 if action == 1 else -10.0
        costh, sinth = np.cos(th), np.sin(th)
        tmp = (force + 0.05 * thd ** 2 * sinth) / 1.1
        thacc = (9.8 * sinth - costh * tmp) / \
            (0.5 * (4.0 / 3.0 - 0.1 * costh ** 2 / 1.1))
        xacc = tmp - 0.05 * thacc * costh / 1.1
        dt = 0.02
        self.s = np.array([x + dt * xd, xd + dt * xacc,
                           th + dt * thd, thd + dt * thacc],
                          dtype=np.float32)
        self.t += 1
        done = bool(abs(self.s[2]) > 0.2095 or abs(self.s[0]) > 2.4
                    or self.t >= 200)
        return self.s.copy(), 1.0, done


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--episodes', type=int, default=40)
    p.add_argument('--gamma', type=float, default=0.99)
    p.add_argument('--lr', type=float, default=0.02)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn

    class Net(nn.HybridBlock):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.dense = nn.Dense(32, activation='relu')
                self.policy = nn.Dense(2)
                self.value = nn.Dense(1)

        def hybrid_forward(self, F, x):
            h = self.dense(x)
            return self.policy(h), self.value(h)

    net = Net()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})
    mx.random.seed(0)
    env = Balance()
    lengths = []
    for ep in range(args.episodes):
        s = env.reset()
        states, actions, rewards = [], [], []
        done = False
        while not done:
            logits, _ = net(nd.array(s.reshape(1, -1)))
            # policy sampled through the framework's seeded RNG
            a = int(nd.sample_multinomial(
                nd.softmax(logits)).asnumpy().ravel()[0])
            s2, r, done = env.step(a)
            states.append(s)
            actions.append(a)
            rewards.append(r)
            s = s2
        # discounted returns, normalized
        R, returns = 0.0, []
        for r in reversed(rewards):
            R = r + args.gamma * R
            returns.append(R)
        returns = np.array(returns[::-1], dtype=np.float32)
        returns = (returns - returns.mean()) / (returns.std() + 1e-6)
        xs = nd.array(np.stack(states))
        acts = nd.array(np.array(actions, dtype=np.float32))
        rets = nd.array(returns)
        with autograd.record():
            logits, values = net(xs)
            logp = nd.log_softmax(logits)
            chosen = nd.pick(logp, acts, axis=1)
            adv = rets - values.reshape((-1,)).detach()
            policy_loss = -(chosen * adv).sum()
            value_loss = nd.square(values.reshape((-1,)) - rets).sum()
            loss = policy_loss + 0.5 * value_loss
        loss.backward()
        trainer.step(len(rewards))
        lengths.append(len(rewards))
        if ep % 10 == 0:
            print('episode %d length %d' % (ep, lengths[-1]))
    early = np.mean(lengths[:10])
    late = np.mean(lengths[-10:])
    print('mean episode length: first10 %.1f last10 %.1f' % (early, late))
    return early, late


if __name__ == '__main__':
    main()
