"""Deep Embedded Clustering (reference:
example/deep-embedded-clustering — pretrain an autoencoder, then
refine the encoder by matching the soft cluster assignment (Student-t
kernel over centroids) to a sharpened target distribution). Returns
(cluster accuracy via majority mapping, baseline chance).
"""
from __future__ import annotations

import argparse

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def gaussian_blobs(rs, n, k, dim, spread=0.18):
    centers = rs.randn(k, dim) * 1.2
    y = rs.randint(0, k, n)
    x = centers[y] + rs.randn(n, dim) * spread
    return x.astype('float32'), y


def cluster_accuracy(assign, labels, k):
    """Majority-vote mapping from clusters to labels."""
    total = 0
    for c in range(k):
        members = labels[assign == c]
        if len(members):
            total += int(np.bincount(members).max())
    return total / len(labels)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--pretrain-epochs', type=int, default=30)
    p.add_argument('--refine-iters', type=int, default=30)
    p.add_argument('--num-samples', type=int, default=512)
    p.add_argument('--clusters', type=int, default=4)
    p.add_argument('--dim', type=int, default=16)
    p.add_argument('--latent', type=int, default=4)
    p.add_argument('--lr', type=float, default=3e-3)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn

    np.random.seed(0)
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    K = args.clusters
    x_np, y_np = gaussian_blobs(rs, args.num_samples, K, args.dim)

    encoder = nn.HybridSequential()
    decoder = nn.HybridSequential()
    with encoder.name_scope():
        encoder.add(nn.Dense(32, activation='relu'),
                    nn.Dense(args.latent))
    with decoder.name_scope():
        decoder.add(nn.Dense(32, activation='relu'),
                    nn.Dense(args.dim))
    for blk in (encoder, decoder):
        blk.initialize(mx.init.Xavier())
    L2 = gluon.loss.L2Loss()
    # two trainers (one per sub-net) keep the script simple
    tr_e = gluon.Trainer(encoder.collect_params(), 'adam',
                         {'learning_rate': args.lr})
    tr_d = gluon.Trainer(decoder.collect_params(), 'adam',
                         {'learning_rate': args.lr})

    xs = nd.array(x_np)
    for _ in range(args.pretrain_epochs):
        with autograd.record():
            loss = L2(decoder(encoder(xs)), xs).mean()
        loss.backward()
        tr_e.step(1)
        tr_d.step(1)

    # init centroids: k-means++ style greedy farthest seeds + 5 Lloyd
    z = encoder(xs).asnumpy()
    cent = [z[rs.randint(len(z))]]
    for _ in range(K - 1):
        d2 = np.min([((z - c) ** 2).sum(1) for c in cent], axis=0)
        cent.append(z[int(d2.argmax())])
    cent = np.stack(cent)
    for _ in range(5):
        assign = ((z[:, None, :] - cent[None]) ** 2).sum(-1).argmin(1)
        for c in range(K):
            if (assign == c).any():
                cent[c] = z[assign == c].mean(0)

    centroids = nd.array(cent)
    centroids.attach_grad()
    tr_c_lr = args.lr

    def soft_assign(zb):
        d2 = ((zb.expand_dims(1) - centroids.expand_dims(0)) ** 2).sum(axis=2)
        q = 1.0 / (1.0 + d2)
        return q / q.sum(axis=1, keepdims=True)

    for _ in range(args.refine_iters):
        # target distribution sharpens confident assignments (DEC eq. 3)
        q_np = soft_assign(encoder(xs)).asnumpy()
        w = (q_np ** 2) / q_np.sum(0, keepdims=True)
        p_np = w / w.sum(1, keepdims=True)
        p_t = nd.array(p_np)
        with autograd.record():
            q = soft_assign(encoder(xs))
            kl = (p_t * (nd.log(p_t + 1e-9) - nd.log(q + 1e-9))) \
                .sum(axis=1).mean()
        kl.backward()
        tr_e.step(1)
        centroids._data = centroids._data - tr_c_lr * \
            centroids.grad._data

    assign = soft_assign(encoder(xs)).asnumpy().argmax(1)
    acc = cluster_accuracy(assign, y_np, K)
    print('DEC cluster accuracy %.3f (chance %.3f)' % (acc, 1.0 / K))
    return acc, 1.0 / K


if __name__ == '__main__':
    main()
