"""Large-margin (SVM) output layer on an MNIST-style task (reference:
example/svm_mnist — replaces SoftmaxOutput with SVMOutput and trains
the same net with hinge loss). Uses the registered SVMOutput op
through the symbolic Module path so the reference script's structure
carries over. Returns accuracy.
"""
from __future__ import annotations

import argparse

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=8)
    p.add_argument('--num-samples', type=int, default=768)
    p.add_argument('--lr', type=float, default=0.1)
    p.add_argument('--regularization', type=float, default=1.0)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import nd

    np.random.seed(0)
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    from examples.multi_task import synth_digits
    x_np, y_np = synth_digits(rs, args.num_samples)
    x_np = x_np.reshape(args.num_samples, -1)

    data = mx.sym.Variable('data')
    h = mx.sym.FullyConnected(data, num_hidden=128, name='fc1')
    h = mx.sym.Activation(h, act_type='relu')
    h = mx.sym.FullyConnected(h, num_hidden=10, name='fc2')
    out = mx.sym.SVMOutput(h, name='svm',
                           regularization_coefficient=args.regularization)

    split = args.num_samples * 3 // 4
    train = mx.io.NDArrayIter(x_np[:split], y_np[:split], batch_size=64,
                              shuffle=True, label_name='svm_label')
    mod = mx.mod.Module(out, label_names=('svm_label',))
    mod.fit(train, num_epoch=args.epochs,
            optimizer_params={'learning_rate': args.lr},
            initializer=mx.init.Xavier())

    scores = mod.predict(mx.io.NDArrayIter(
        x_np[split:], y_np[split:], batch_size=64,
        label_name='svm_label')).asnumpy()
    acc = float((scores[:len(y_np) - split].argmax(1) ==
                 y_np[split:]).mean())
    print('svm_mnist accuracy %.3f' % acc)
    return acc


if __name__ == '__main__':
    main()
