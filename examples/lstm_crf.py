"""BiLSTM-CRF sequence tagger with Viterbi decode (reference:
example/gluon/lstm_crf/lstm_crf.py — per-timestep host Python loops,
one sentence at a time, nd.asscalar() inside the forward algorithm).

TPU-native redesign: the CRF lattice recursions become batched
contrib.foreach scans (ONE lax.scan each) over the time axis —
log-sum-exp forward algorithm for the partition function, max-product
for Viterbi — with tag-transition scores as a Parameter. START/STOP
are explicit transition VECTORS instead of padded tag rows, so every
lattice op stays a dense [B, K, K] broadcast on static shapes.

jit-cache note: sentences are bucketed by padded length; each bucket
length compiles once (the scan length is part of the trace signature).
The Viterbi backtrace (argmax chain over the stacked backpointers) runs
on host numpy at decode time — it is inference-only, O(T*B) ints, and
keeping it off-device avoids a gather-chain program for no benefit.
"""
from __future__ import annotations

import argparse

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np

TAGS = ['O', 'B', 'I']


def make_corpus(rs, n, vocab, seq_len):
    """Entity tokens live in [10, 30); chunks tag B,I,I..."""
    x = rs.randint(30, vocab, (n, seq_len))
    y = np.zeros((n, seq_len), np.int64)
    for i in range(n):
        for _ in range(rs.randint(1, 3)):
            length = rs.randint(1, 4)
            start = rs.randint(0, seq_len - length)
            x[i, start:start + length] = rs.randint(10, 30, length)
            y[i, start] = 1
            y[i, start + 1:start + length] = 2
    return x, y


def build_model(vocab, embed, hidden, K):
    from mxnet_tpu.gluon import HybridBlock, nn, rnn

    class BiLSTMCRF(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = nn.Embedding(vocab, embed)
                self.lstm = rnn.LSTM(hidden, bidirectional=True,
                                     layout='NTC')
                self.proj = nn.Dense(K, flatten=False, prefix='proj_')
                # trans[i, j] = score of moving TO tag i FROM tag j
                # (reference layout, lstm_crf.py transitions)
                self.trans = self.params.get('crf_transitions',
                                             shape=(K, K), init='zeros')
                self.start = self.params.get('crf_start', shape=(K,),
                                             init='zeros')
                self.stop = self.params.get('crf_stop', shape=(K,),
                                            init='zeros')
            self._K = K

        def feats(self, x):
            return self.proj(self.lstm(self.embed(x)))   # (B, T, K)

        def hybrid_forward(self, F, x, tags, trans=None, start=None,
                           stop=None):
            """Returns the batched CRF negative log-likelihood."""
            K = self._K
            feats = self.feats(x)                        # (B, T, K)
            f_t = F.transpose(feats, axes=(1, 0, 2))     # (T, B, K)

            # -- partition function: logsumexp lattice scan ------------
            alpha0 = F.reshape(start, shape=(1, K)) + \
                F.squeeze(F.slice_axis(f_t, axis=0, begin=0, end=1),
                          axis=0)                        # (B, K)

            def fwd_body(data, states):
                feat = data                              # (B, K)
                alpha = states[0]
                # scores[b, i, j] = alpha[b, j] + trans[i, j]
                scores = F.expand_dims(alpha, axis=1) + \
                    F.expand_dims(trans, axis=0)         # (B, K, K)
                m = F.max(scores, axis=2)                # (B, K)
                new = m + F.log(F.sum(
                    F.exp(scores - F.expand_dims(m, axis=2)), axis=2))
                new = new + feat
                return [new], [new]

            rest = F.slice_axis(f_t, axis=0, begin=1,
                                end=f_t.shape[0])
            _o, fin = F.contrib.foreach(fwd_body, rest, [alpha0])
            alpha_T = fin[0]                             # (B, K)
            m = F.max(alpha_T + F.reshape(stop, shape=(1, K)), axis=1)
            log_z = m + F.log(F.sum(
                F.exp(alpha_T + F.reshape(stop, shape=(1, K))
                      - F.expand_dims(m, axis=1)), axis=1))

            # -- gold path score (vectorized one_hot picks) ------------
            oh = F.one_hot(tags, depth=K)                # (B, T, K)
            emit = F.sum(feats * oh, axis=(1, 2))        # (B,)
            oh_t = F.transpose(oh, axes=(1, 0, 2))       # (T, B, K)
            prev = F.slice_axis(oh_t, axis=0, begin=0,
                                end=oh_t.shape[0] - 1)
            nxt = F.slice_axis(oh_t, axis=0, begin=1,
                               end=oh_t.shape[0])
            # trans score per step: nxt_i * trans[i,j] * prev_j
            tr = F.sum(F.expand_dims(nxt, axis=3)
                       * F.reshape(trans, shape=(1, 1, K, K))
                       * F.expand_dims(prev, axis=2), axis=(0, 2, 3))
            first = F.squeeze(F.slice_axis(oh_t, axis=0, begin=0,
                                           end=1), axis=0)
            last = F.squeeze(F.slice_axis(oh_t, axis=0,
                                          begin=oh_t.shape[0] - 1,
                                          end=oh_t.shape[0]), axis=0)
            score = emit + tr + F.sum(first * start, axis=1) \
                + F.sum(last * stop, axis=1)
            return F.mean(log_z - score)

        def viterbi(self, x):
            """Max-product recursion; backtrace on host numpy."""
            feats = self.feats(x)
            f_np = feats.asnumpy()                       # (B, T, K)
            trans = self.trans.data().asnumpy()
            start = self.start.data().asnumpy()
            stop = self.stop.data().asnumpy()
            B, T, _ = f_np.shape
            delta = start[None, :] + f_np[:, 0]          # (B, K)
            bptr = np.zeros((T - 1, B, K), np.int64)
            for t in range(1, T):
                scores = delta[:, None, :] + trans[None, :, :]
                bptr[t - 1] = scores.argmax(2)
                delta = scores.max(2) + f_np[:, t]
            best_last = (delta + stop[None, :]).argmax(1)
            path = np.zeros((B, T), np.int64)
            path[:, -1] = best_last
            for t in range(T - 2, -1, -1):
                path[:, t] = bptr[t][np.arange(B), path[:, t + 1]]
            return path

    return BiLSTMCRF()


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=30)
    p.add_argument('--num-samples', type=int, default=256)
    p.add_argument('--vocab', type=int, default=100)
    p.add_argument('--seq-len', type=int, default=10)
    p.add_argument('--hidden', type=int, default=32)
    p.add_argument('--lr', type=float, default=0.01)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    rs = np.random.RandomState(0)
    x_np, y_np = make_corpus(rs, args.num_samples, args.vocab,
                             args.seq_len)
    net = build_model(args.vocab, 16, args.hidden, len(TAGS))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), 'adam',
                       {'learning_rate': args.lr})
    x_nd, y_nd = nd.array(x_np), nd.array(y_np)
    B = args.num_samples
    for _ in range(args.epochs):
        with autograd.record():
            nll = net(x_nd, y_nd)
        nll.backward()
        tr.step(1)     # nll is already a mean
    path = net.viterbi(x_nd)
    acc = float((path == y_np).mean())
    print('lstm_crf viterbi accuracy %.3f' % acc)
    return acc


if __name__ == '__main__':
    main()
