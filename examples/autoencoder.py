"""Stacked autoencoder with layer-wise pretraining then fine-tuning —
the representation-learning workload (reference: example/autoencoder/
autoencoder.py + deep-embedded-clustering). Synthetic clustered data;
reports reconstruction error and cluster purity of the embedding.
"""
from __future__ import annotations

import argparse

# shared standalone-run bootstrap (repo root onto sys.path); when
# imported as examples.* the root is already importable and the
# script dir is not on sys.path, so gate on standalone execution
if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def clustered_data(rs, n, dim, k):
    centers = rs.randn(k, dim).astype(np.float32) * 3
    y = rs.randint(0, k, n)
    x = centers[y] + rs.randn(n, dim).astype(np.float32)
    return x, y


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--num-samples', type=int, default=1024)
    p.add_argument('--dim', type=int, default=32)
    p.add_argument('--clusters', type=int, default=4)
    p.add_argument('--latent', type=int, default=2)
    p.add_argument('--batch-size', type=int, default=64)
    p.add_argument('--epochs', type=int, default=10)
    p.add_argument('--lr', type=float, default=0.01)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn

    rs = np.random.RandomState(0)
    x_all, y_all = clustered_data(rs, args.num_samples, args.dim,
                                  args.clusters)

    class AE(nn.HybridBlock):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.enc1 = nn.Dense(16, activation='relu')
                self.enc2 = nn.Dense(args.latent)
                self.dec1 = nn.Dense(16, activation='relu')
                self.dec2 = nn.Dense(args.dim)

        def encode(self, x):
            return self.enc2(self.enc1(x))

        def hybrid_forward(self, F, x):
            return self.dec2(self.dec1(self.encode(x)))

    net = AE()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})
    L = gluon.loss.L2Loss()

    mse = None
    for epoch in range(args.epochs):
        order = rs.permutation(args.num_samples)
        tot = cnt = 0
        for b in range(0, args.num_samples, args.batch_size):
            xb = nd.array(x_all[order[b:b + args.batch_size]])
            with autograd.record():
                loss = L(net(xb), xb)
            loss.backward()
            trainer.step(xb.shape[0])
            tot += float(loss.mean().asscalar())
            cnt += 1
        mse = tot / cnt
    print('final reconstruction loss %.4f' % mse)

    # embedding quality: nearest-centroid purity in latent space
    z = net.encode(nd.array(x_all)).asnumpy()
    cents = np.stack([z[y_all == c].mean(0)
                      for c in range(args.clusters)])
    assign = np.argmin(((z[:, None, :] - cents[None]) ** 2).sum(-1), 1)
    purity = (assign == y_all).mean()
    print('latent nearest-centroid purity %.3f' % purity)
    assert np.isfinite(mse)
    return mse, purity


if __name__ == '__main__':
    main()
