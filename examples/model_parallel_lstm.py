"""Model-parallel LSTM (reference: example/model-parallel-lstm — layers
of a stacked LSTM placed on different devices). The TPU-native
expression: the stacked-LSTM projection weights shard over a 'tp' mesh
axis while the batch shards over 'dp', all inside one pjit-compiled
ParallelTrainer step — placement by sharding annotation instead of
per-layer ctx assignment. Returns (final loss, first loss).
"""
from __future__ import annotations

import argparse

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--steps', type=int, default=20)
    p.add_argument('--vocab', type=int, default=32)
    p.add_argument('--seq-len', type=int, default=12)
    p.add_argument('--hidden', type=int, default=64)
    p.add_argument('--layers', type=int, default=2)
    p.add_argument('--dp', type=int, default=2)
    p.add_argument('--tp', type=int, default=2)
    args = p.parse_args(argv)

    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, parallel
    from mxnet_tpu.gluon import nn, rnn

    np.random.seed(0)
    mx.random.seed(0)
    n_dev = args.dp * args.tp
    try:
        cpu_devs = jax.devices('cpu')
    except RuntimeError:          # cpu platform filtered out
        cpu_devs = []
    devices = cpu_devs[:n_dev] if len(cpu_devs) >= n_dev \
        else jax.devices()[:n_dev]
    if len(devices) < n_dev:
        raise SystemExit('need %d devices (set XLA_FLAGS='
                         '--xla_force_host_platform_device_count)' % n_dev)
    mesh = parallel.create_mesh({'dp': args.dp, 'tp': args.tp},
                                devices=devices)

    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Embedding(args.vocab, 24),
                rnn.LSTM(args.hidden, num_layers=args.layers,
                         layout='NTC'),
                nn.Dense(args.vocab, flatten=False))
    net.initialize(mx.init.Xavier())

    rs = np.random.RandomState(0)
    batch = 8 * args.dp
    x_np = rs.randint(0, args.vocab, (batch, args.seq_len))
    # next-token labels of a fixed cyclic language: learnable quickly
    y_np = (x_np + 1) % args.vocab

    L = gluon.loss.SoftmaxCrossEntropyLoss()

    def seq_loss(out, label):
        return L(out.reshape((-1, args.vocab)),
                 label.reshape((-1,))).mean()

    pt = parallel.ParallelTrainer(net, seq_loss, 'adam',
                                  {'learning_rate': 5e-3}, mesh)
    xs, ys = nd.array(x_np), nd.array(y_np.astype('float32'))
    first = last = None
    for _ in range(args.steps):
        last = float(pt.step(xs, ys).asscalar())
        if first is None:
            first = last
    print('model-parallel lstm (dp=%d tp=%d): loss %.4f -> %.4f'
          % (args.dp, args.tp, first, last))
    return last, first


if __name__ == '__main__':
    main()
