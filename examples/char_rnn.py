"""Character-level language model (reference: example/rnn char-rnn —
an LSTM over character streams, sampled after training). A tiny
synthetic grammar ("abcabc..." cycles with random separators) keeps
it self-contained; the model must learn the cycle to beat the
character-frequency baseline. Returns (bits-per-char, baseline bpc).
"""
from __future__ import annotations

import argparse

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np

ALPHABET = 'abcdef .'


def make_text(rs, length):
    out = []
    while len(out) < length:
        out.extend('abcdef' * rs.randint(1, 4))
        out.append(' ' if rs.rand() < 0.7 else '.')
    return ''.join(out[:length])


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=14)
    p.add_argument('--corpus-len', type=int, default=4000)
    p.add_argument('--seq-len', type=int, default=24)
    p.add_argument('--hidden', type=int, default=64)
    p.add_argument('--lr', type=float, default=5e-3)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn, rnn

    np.random.seed(0)
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    text = make_text(rs, args.corpus_len)
    V = len(ALPHABET)
    codes = np.array([ALPHABET.index(c) for c in text])
    L = args.seq_len
    n_seq = (len(codes) - 1) // L
    x_np = codes[:n_seq * L].reshape(n_seq, L)
    y_np = codes[1:n_seq * L + 1].reshape(n_seq, L)

    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Embedding(V, 16),
                rnn.LSTM(args.hidden, layout='NTC'),
                nn.Dense(V, flatten=False))
    net.initialize(mx.init.Xavier())
    L_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})

    split = n_seq * 3 // 4
    xs, ys = nd.array(x_np), nd.array(y_np.astype('float32'))
    batch = 32
    for _ in range(args.epochs):
        for i in range(0, split, batch):
            xb, yb = xs[i:i + batch], ys[i:i + batch]
            with autograd.record():
                logits = net(xb)
                loss = L_fn(logits.reshape((-1, V)), yb.reshape((-1,)))
            loss.backward()
            trainer.step(xb.shape[0])

    logits = net(xs[split:]).asnumpy().reshape(-1, V)
    gold = y_np[split:].reshape(-1)
    logp = logits - np.log(np.exp(logits - logits.max(1, keepdims=True))
                           .sum(1, keepdims=True)) - \
        logits.max(1, keepdims=True)
    bpc = float(-logp[np.arange(len(gold)), gold].mean() / np.log(2))
    freq = np.bincount(codes, minlength=V) / len(codes)
    base = float(-np.log2(freq[gold] + 1e-12).mean())
    print('char-rnn bits/char %.3f (frequency baseline %.3f)'
          % (bpc, base))
    return bpc, base


if __name__ == '__main__':
    main()
