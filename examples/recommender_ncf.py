"""Neural collaborative filtering recommender (reference:
example/recommenders — MF + MLP hybrid over user/item embeddings,
implicit-feedback ranking). Synthetic taste model: users and items
live in a latent genre space; a user likes items whose genre matches.
Returns (AUC, chance AUC 0.5).
"""
from __future__ import annotations

import argparse

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=30)
    p.add_argument('--users', type=int, default=64)
    p.add_argument('--items', type=int, default=96)
    p.add_argument('--interactions', type=int, default=2048)
    p.add_argument('--embed', type=int, default=12)
    p.add_argument('--lr', type=float, default=0.01)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn

    np.random.seed(0)
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    genres = 4
    u_genre = rs.randint(0, genres, args.users)
    i_genre = rs.randint(0, genres, args.items)
    users = rs.randint(0, args.users, args.interactions)
    items = rs.randint(0, args.items, args.interactions)
    match = (u_genre[users] == i_genre[items])
    noise = rs.rand(args.interactions) < 0.1
    y_np = (match ^ noise).astype('float32')

    class NCF(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.u_mf = nn.Embedding(args.users, args.embed)
                self.i_mf = nn.Embedding(args.items, args.embed)
                self.u_mlp = nn.Embedding(args.users, args.embed)
                self.i_mlp = nn.Embedding(args.items, args.embed)
                self.mlp = nn.HybridSequential()
                self.mlp.add(nn.Dense(32, activation='relu'),
                             nn.Dense(16, activation='relu'))
                self.out = nn.Dense(1)

        def hybrid_forward(self, F, u, i):
            mf = self.u_mf(u) * self.i_mf(i)
            mlp = self.mlp(F.concat(self.u_mlp(u), self.i_mlp(i),
                                    dim=1))
            return self.out(F.concat(mf, mlp, dim=1)).reshape((-1,))

    net = NCF()
    net.initialize(mx.init.Xavier())
    L_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})

    split = args.interactions * 3 // 4
    us, is_, ys = nd.array(users), nd.array(items), nd.array(y_np)
    batch = 128
    for _ in range(args.epochs):
        for i in range(0, split, batch):
            ub, ib, yb = (us[i:i + batch], is_[i:i + batch],
                          ys[i:i + batch])
            with autograd.record():
                loss = L_fn(net(ub, ib), yb)
            loss.backward()
            trainer.step(ub.shape[0])

    scores = net(us[split:], is_[split:]).asnumpy()
    gold = y_np[split:]
    # AUC by rank statistic
    order = np.argsort(scores)
    ranks = np.empty_like(order, dtype='float64')
    ranks[order] = np.arange(1, len(scores) + 1)
    n_pos, n_neg = int(gold.sum()), int((1 - gold).sum())
    auc = (ranks[gold == 1].sum() - n_pos * (n_pos + 1) / 2) / \
        max(1, n_pos * n_neg)
    print('ncf recommender AUC %.3f (chance 0.5)' % auc)
    return float(auc), 0.5


if __name__ == '__main__':
    main()
