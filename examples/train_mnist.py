"""LeNet on MNIST — the reference's canonical example
(example/image-classification/train_mnist.py), on both training APIs:
Module.fit over the symbolic graph, and Gluon with a hybridized net +
fused trainer. Falls back to synthetic digits when no MNIST files exist
(zero-egress environments).
"""
from __future__ import annotations

import argparse
import os

# shared standalone-run bootstrap (repo root onto sys.path); when
# imported as examples.* the root is already importable and the
# script dir is not on sys.path, so gate on standalone execution
if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def get_data(batch_size, data_dir=None):
    import mxnet_tpu as mx
    files = ['train-images-idx3-ubyte', 'train-labels-idx1-ubyte']
    if data_dir and all(os.path.exists(os.path.join(data_dir, f))
                        for f in files):
        train = mx.io.MNISTIter(
            image=os.path.join(data_dir, files[0]),
            label=os.path.join(data_dir, files[1]),
            batch_size=batch_size, shuffle=True)
        return train, train
    # synthetic "digits": class k = a bright kxk top-left square
    rs = np.random.RandomState(0)
    n = 2048
    y = rs.randint(0, 10, n)
    x = rs.rand(n, 1, 28, 28).astype('float32') * 0.1
    for i, k in enumerate(y):
        x[i, 0, :k + 2, :k + 2] += 0.9
    train = mx.io.NDArrayIter(x, y.astype('float32'),
                              batch_size=batch_size, shuffle=True,
                              label_name='softmax_label')
    return train, train


def lenet_symbol():
    import mxnet_tpu as mx
    data = mx.sym.Variable('data')
    c1 = mx.sym.Convolution(data, kernel=(5, 5), num_filter=20,
                            name='conv1')
    t1 = mx.sym.Activation(c1, act_type='tanh', name='tanh1')
    p1 = mx.sym.Pooling(t1, pool_type='max', kernel=(2, 2), stride=(2, 2),
                        name='pool1')
    c2 = mx.sym.Convolution(p1, kernel=(5, 5), num_filter=50,
                            name='conv2')
    t2 = mx.sym.Activation(c2, act_type='tanh', name='tanh2')
    p2 = mx.sym.Pooling(t2, pool_type='max', kernel=(2, 2), stride=(2, 2),
                        name='pool2')
    fl = mx.sym.Flatten(p2, name='flatten')
    f1 = mx.sym.FullyConnected(fl, num_hidden=500, name='fc1')
    t3 = mx.sym.Activation(f1, act_type='tanh', name='tanh3')
    f2 = mx.sym.FullyConnected(t3, num_hidden=10, name='fc2')
    return mx.sym.SoftmaxOutput(f2, name='softmax')


def train_module(epochs, batch_size, lr, data_dir=None):
    import mxnet_tpu as mx
    train, val = get_data(batch_size, data_dir)
    mod = mx.mod.Module(lenet_symbol(), data_names=['data'],
                        label_names=['softmax_label'])
    mod.fit(train, eval_data=val, num_epoch=epochs, optimizer='sgd',
            optimizer_params={'learning_rate': lr, 'momentum': 0.9},
            initializer=mx.init.Xavier(),
            batch_end_callback=mx.callback.Speedometer(batch_size, 50),
            eval_metric='acc')
    metric = mx.metric.Accuracy()
    val.reset()
    acc = mod.score(val, metric)
    return dict(acc)['accuracy']


def train_gluon(epochs, batch_size, lr, data_dir=None):
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn
    train, _ = get_data(batch_size, data_dir)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(20, 5, activation='tanh'),
                nn.MaxPool2D(2, 2),
                nn.Conv2D(50, 5, activation='tanh'),
                nn.MaxPool2D(2, 2), nn.Flatten(),
                nn.Dense(500, activation='tanh'), nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net.hybridize(static_alloc=True, static_shape=True)
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': lr, 'momentum': 0.9})
    metric = mx.metric.Accuracy()
    for epoch in range(epochs):
        train.reset()
        metric.reset()
        for batch in train:
            x, y = batch.data[0], batch.label[0]
            with autograd.record():
                out = net(x)
                loss = L(out, y)
            loss.backward()
            trainer.step(batch_size)
            metric.update([y], [out])
    return metric.get()[1]


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--api', choices=['module', 'gluon'], default='module')
    p.add_argument('--epochs', type=int, default=3)
    p.add_argument('--batch-size', type=int, default=64)
    p.add_argument('--lr', type=float, default=0.05)
    p.add_argument('--data-dir', default=None)
    args = p.parse_args()
    fn = train_module if args.api == 'module' else train_gluon
    acc = fn(args.epochs, args.batch_size, args.lr, args.data_dir)
    print('final accuracy %.4f' % acc)


if __name__ == '__main__':
    main()
