"""Encoder-decoder sequence-to-sequence learning (reference:
example/rnn — the bucketing/encoder-decoder stack; example/nmt-style
teacher forcing). Tiny TPU-native rendition: a GRU encoder consumes
the source, its final state seeds a GRU decoder trained with teacher
forcing to emit the REVERSED sequence — the classic seq2seq sanity
task that requires the bottleneck state to carry the whole sequence.
Uses the gluon.rnn cell zoo's step/unroll API directly. Returns
(token accuracy, chance).
"""
from __future__ import annotations

import argparse

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=30)
    p.add_argument('--num-samples', type=int, default=256)
    p.add_argument('--vocab', type=int, default=6)
    p.add_argument('--seq-len', type=int, default=5)
    p.add_argument('--hidden', type=int, default=48)
    p.add_argument('--lr', type=float, default=0.01)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn, rnn

    np.random.seed(0)
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    V, L = args.vocab, args.seq_len
    src = rs.randint(0, V, (args.num_samples, L))
    tgt = src[:, ::-1].copy()

    class Seq2Seq(gluon.Block):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                # V + 1 rows: id V is the BOS marker
                self.embed = nn.Embedding(V + 1, 16)
                self.encoder = rnn.GRUCell(args.hidden)
                self.decoder = rnn.GRUCell(args.hidden)
                self.proj = nn.Dense(V, flatten=False)

        def forward(self, source, target_in):
            emb = self.embed(source)              # (B, L, 16)
            _, enc_state = self.encoder.unroll(
                L, emb, layout='NTC', merge_outputs=True)
            dec_emb = self.embed(target_in)
            outs, _ = self.decoder.unroll(
                L, dec_emb, begin_state=enc_state, layout='NTC',
                merge_outputs=True)
            return self.proj(outs)                # (B, L, V)

    net = Seq2Seq()
    net.initialize(mx.init.Xavier())
    L_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})

    # teacher forcing: decoder input is the gold target shifted right,
    # position 0 seeing a dedicated BOS id (V) so no label leaks in
    bos = np.full((args.num_samples, 1), V)
    tgt_in = np.concatenate([bos, tgt[:, :-1]], axis=1)
    split = args.num_samples * 3 // 4
    xs, ti, ys = nd.array(src), nd.array(tgt_in), nd.array(tgt)
    batch = 64
    for _ in range(args.epochs):
        for i in range(0, split, batch):
            xb, tb, yb = (xs[i:i + batch], ti[i:i + batch],
                          ys[i:i + batch])
            with autograd.record():
                logits = net(xb, tb)
                loss = L_fn(logits.reshape((-1, V)),
                            yb.reshape((-1,)))
            loss.backward()
            trainer.step(xb.shape[0])   # honest scale on partial batches

    pred = net(xs[split:], ti[split:]).asnumpy().argmax(axis=-1)
    acc = float((pred == tgt[split:]).mean())
    print('seq2seq reverse token accuracy %.3f (chance %.3f)'
          % (acc, 1.0 / V))
    return acc, 1.0 / V


if __name__ == '__main__':
    main()
