"""Noise-contrastive estimation language model (reference:
example/nce-loss — train a word model with sampled negatives instead
of a full-vocabulary softmax). TPU-native rendition: the per-batch
negative sample set is drawn on host and gathered with one Embedding
lookup, so the NCE logits are a single small matmul per step — the
full-vocab softmax never materialises. Returns (full-softmax
perplexity proxy, nce-trained accuracy) on a synthetic bigram corpus.
"""
from __future__ import annotations

import argparse

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=8)
    p.add_argument('--vocab', type=int, default=60)
    p.add_argument('--corpus-len', type=int, default=2000)
    p.add_argument('--dim', type=int, default=24)
    p.add_argument('--num-negatives', type=int, default=8)
    p.add_argument('--lr', type=float, default=0.05)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn

    rs = np.random.RandomState(0)
    V = args.vocab
    # deterministic bigram structure: w -> (w*7+3) % V most of the time
    ctx_words = rs.randint(0, V, args.corpus_len)
    nxt = np.where(rs.rand(args.corpus_len) < 0.85,
                   (ctx_words * 7 + 3) % V,
                   rs.randint(0, V, args.corpus_len))

    class NCEModel(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = nn.Embedding(V, args.dim)
                self.out_embed = nn.Embedding(V, args.dim)
                self.out_bias = nn.Embedding(V, 1)

        def hybrid_forward(self, F, ctx_ids, cand_ids):
            h = self.embed(ctx_ids)                      # (B, D)
            w = self.out_embed(cand_ids)                 # (B, K, D)
            b = self.out_bias(cand_ids).reshape((0, -1))  # (B, K)
            # (B, 1, D) x (B, K, D) -> per-candidate logits
            return (F.expand_dims(h, axis=1) * w).sum(axis=-1) + b

    net = NCEModel()
    net.initialize(mx.init.Xavier())
    L = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})

    batch = 64
    K = args.num_negatives
    for _ in range(args.epochs):
        order = rs.permutation(args.corpus_len)
        for i in range(0, args.corpus_len, batch):
            idx = order[i:i + batch]
            ctx_b = ctx_words[idx]
            pos = nxt[idx]
            # candidates: true next word + K noise draws
            noise = rs.randint(0, V, (len(idx), K))
            cands = np.concatenate([pos[:, None], noise], axis=1)
            labels = np.zeros((len(idx), K + 1), 'float32')
            labels[:, 0] = 1.0
            with autograd.record():
                logits = net(nd.array(ctx_b), nd.array(cands))
                loss = L(logits, nd.array(labels))
            loss.backward()
            trainer.step(len(idx))

    # full-vocab scoring at eval (small): accuracy of argmax next word
    all_ids = nd.array(np.arange(V))
    emb = net.embed(nd.array(ctx_words[:512])).asnumpy()
    out_w = net.out_embed(all_ids).asnumpy()
    out_b = net.out_bias(all_ids).asnumpy().ravel()
    scores = emb @ out_w.T + out_b
    acc = float((scores.argmax(axis=1) == nxt[:512]).mean())
    print('nce next-word accuracy %.3f (chance %.3f)' % (acc, 1.0 / V))
    return acc, 1.0 / V


if __name__ == '__main__':
    main()
