"""ImageNet-style training pipeline (reference:
example/image-classification/train_imagenet.py:66 — the flagship
script: images on disk → im2rec packing → ImageRecordIter with
augmentation → fit). This rendition drives the SAME pipeline stages:
a folder tree of class images, `tools/im2rec` packing to .rec, an
augmenting ImageRecordIter, and Module.fit over a resnet — at toy
scale so it runs anywhere, with `--benchmark` synthesizing data the
way the reference's --benchmark 1 does. Returns top-1 validation
accuracy.
"""
from __future__ import annotations

import argparse
import os
import tempfile

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def synth_image_tree(root, rs, classes, per_class, size=48):
    """Class-distinct JPEG tree: class k gets a k-dependent color patch
    grid — learnable from pixels alone."""
    import cv2
    for k in range(classes):
        d = os.path.join(root, 'class_%02d' % k)
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            img = (rs.rand(size, size, 3) * 60).astype('uint8')
            r, c = (k * 11) % (size - 16), (k * 7) % (size - 16)
            color = [(k * 37) % 200 + 55, (k * 73) % 200 + 55,
                     (k * 11) % 200 + 55]
            img[r:r + 16, c:c + 16] = color
            cv2.imwrite(os.path.join(d, '%03d.jpg' % i), img)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--classes', type=int, default=4)
    p.add_argument('--per-class', type=int, default=24)
    p.add_argument('--batch-size', type=int, default=16)
    p.add_argument('--num-epochs', type=int, default=8)
    p.add_argument('--image-shape', default='3,32,32')
    p.add_argument('--network', default='resnet18_v1')
    p.add_argument('--lr', type=float, default=0.005)
    p.add_argument('--data-dir', default=None,
                   help='existing image folder tree (default: synthesize)')
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu.tools import im2rec
    from mxnet_tpu.gluon import model_zoo

    np.random.seed(0)
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    shape = tuple(int(s) for s in args.image_shape.split(','))

    workdir = tempfile.mkdtemp(prefix='imagenet_toy_')
    data_dir = args.data_dir
    if data_dir is None:
        data_dir = os.path.join(workdir, 'imgs')
        synth_image_tree(data_dir, rs, args.classes, args.per_class,
                         size=max(shape[1] + 16, 48))

    # stage 1: list + pack (the reference's im2rec step)
    prefix = os.path.join(workdir, 'data')
    im2rec.main([prefix, data_dir, '--list', '--recursive',
                 '--train-ratio', '0.75'])
    for part in ('train', 'val'):
        im2rec.main(['%s_%s' % (prefix, part), data_dir,
                     '--resize', str(shape[1] + 8)])

    # stage 2: augmenting record iterators
    common = dict(data_shape=shape, batch_size=args.batch_size,
                  label_width=1)
    train = mx.io.ImageRecordIter(
        path_imgrec=prefix + '_train.rec', shuffle=True, rand_crop=True,
        rand_mirror=True, **common)
    val = mx.io.ImageRecordIter(path_imgrec=prefix + '_val.rec',
                                **common)

    # stage 3: symbolic net + Module.fit (train_imagenet.py's fit call)
    import mxnet_tpu.symbol  # noqa: F401
    net = model_zoo.vision.get_resnet(
        1, int(args.network.replace('resnet', '').split('_')[0]),
        classes=args.classes, thumbnail=True)
    data = mx.sym.Variable('data')
    sym = net(data) if hasattr(net, '__call__') else None
    out = mx.sym.SoftmaxOutput(sym, name='softmax')

    mod = mx.mod.Module(out, label_names=('softmax_label',))
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            optimizer='sgd',
            optimizer_params={'learning_rate': args.lr, 'momentum': 0.9},
            initializer=mx.init.Xavier(rnd_type='gaussian',
                                       factor_type='in', magnitude=2),
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, 10))

    metric = mx.metric.Accuracy()
    val.reset()
    score = mod.score(val, metric)
    acc = dict(score)['accuracy'] if isinstance(score, list) else \
        metric.get()[1]
    print('train_imagenet top-1 val accuracy %.3f (%d classes)'
          % (acc, args.classes))
    return acc


if __name__ == '__main__':
    main()
