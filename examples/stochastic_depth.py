"""Stochastic depth training (reference: example/stochastic-depth —
residual blocks randomly dropped during training with linearly
decaying survival probability; all blocks active, scaled, at test
time). Returns (accuracy, mean survival prob).
"""
from __future__ import annotations

import argparse

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=10)
    p.add_argument('--num-samples', type=int, default=512)
    p.add_argument('--blocks', type=int, default=6)
    p.add_argument('--min-survival', type=float, default=0.5)
    p.add_argument('--lr', type=float, default=2e-3)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn

    np.random.seed(0)
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    from examples.multi_task import synth_digits
    x_np, y_np = synth_digits(rs, args.num_samples)

    # survival probability decays linearly with depth (Huang 2016)
    survival = [1.0 - (1.0 - args.min_survival) * b / (args.blocks - 1)
                for b in range(args.blocks)]

    class StochasticResBlock(gluon.Block):
        def __init__(self, channels, p_survive, **kw):
            super().__init__(**kw)
            self.p_survive = p_survive
            with self.name_scope():
                self.conv1 = nn.Conv2D(channels, 3, padding=1,
                                       activation='relu')
                self.conv2 = nn.Conv2D(channels, 3, padding=1)

        def forward(self, x):
            if autograd.is_training():
                if np.random.rand() > self.p_survive:
                    return x                     # block dropped whole
                return nd.relu(x + self.conv2(self.conv1(x)))
            # inference: expected-value scaling
            return nd.relu(x + self.p_survive *
                           self.conv2(self.conv1(x)))

    class Net(gluon.Block):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.stem = nn.Conv2D(16, 3, padding=1,
                                      activation='relu')
                self.blocks = []
                for b in range(args.blocks):
                    blk = StochasticResBlock(16, survival[b])
                    self.register_child(blk, 'block%d' % b)
                    self.blocks.append(blk)
                self.head = nn.HybridSequential()
                # the synthetic classes are position-coded: keep the
                # spatial layout (flatten), don't average it away
                self.head.add(nn.MaxPool2D(2), nn.Flatten(),
                              nn.Dense(64, activation='relu'),
                              nn.Dense(10))

        def forward(self, x):
            h = self.stem(x)
            for blk in self.blocks:
                h = blk(h)
            return self.head(h)

    net = Net()
    net.initialize(mx.init.Xavier())
    # one inference pass visits EVERY block (no dropping outside
    # training), finishing deferred shape inference before blocks can
    # be skipped
    net(nd.array(x_np[:2]))
    L_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})

    split = args.num_samples * 3 // 4
    xs, ys = nd.array(x_np), nd.array(y_np)
    batch = 64
    for _ in range(args.epochs):
        for i in range(0, split, batch):
            xb, yb = xs[i:i + batch], ys[i:i + batch]
            with autograd.record():
                loss = L_fn(net(xb), yb)
            loss.backward()
            # dropped blocks leave stale grads by design
            trainer.step(xb.shape[0], ignore_stale_grad=True)

    pred = net(xs[split:]).asnumpy().argmax(1)
    acc = float((pred == y_np[split:]).mean())
    print('stochastic-depth accuracy %.3f (mean survival %.2f)'
          % (acc, float(np.mean(survival))))
    return acc, float(np.mean(survival))


if __name__ == '__main__':
    main()
