"""FCN-style semantic segmentation (reference: example/fcn-xs — VGG
encoder + 1x1 score conv + Deconvolution bilinear upsampling). Tiny
TPU-native rendition: conv encoder downsamples 2x, a 1x1 conv scores
classes, a stride-2 Deconvolution (bilinear-initialised) restores full
resolution; trained end-to-end with per-pixel softmax CE on synthetic
two-shape scenes. Returns (pixel_accuracy, majority_baseline).
"""
from __future__ import annotations

import argparse

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def _scenes(rs, n, size):
    """Images with a bright square (class 1) and circle (class 2) on a
    noisy background (class 0)."""
    x = rs.rand(n, 1, size, size).astype('float32') * 0.2
    y = np.zeros((n, size, size), 'float32')
    for i in range(n):
        s = rs.randint(size // 4, size // 2)
        r0, c0 = rs.randint(0, size - s, 2)
        x[i, 0, r0:r0 + s, c0:c0 + s] += 0.8
        y[i, r0:r0 + s, c0:c0 + s] = 1
        rad = rs.randint(size // 8, size // 4)
        cy, cx = rs.randint(rad, size - rad, 2)
        yy, xx = np.ogrid[:size, :size]
        disk = (yy - cy) ** 2 + (xx - cx) ** 2 <= rad ** 2
        x[i, 0][disk] = -0.6
        y[i][disk] = 2
    return x, y


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=12)
    p.add_argument('--num-samples', type=int, default=64)
    p.add_argument('--size', type=int, default=32)
    p.add_argument('--lr', type=float, default=0.02)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn

    n_class = 3
    rs = np.random.RandomState(0)
    X, Y = _scenes(rs, args.num_samples, args.size)

    class FCN(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.encoder = nn.HybridSequential()
                self.encoder.add(
                    nn.Conv2D(16, 3, padding=1, activation='relu'),
                    nn.MaxPool2D(2),
                    nn.Conv2D(32, 3, padding=1, activation='relu'))
                self.score = nn.Conv2D(n_class, 1)
                # learnable stride-2 upsampling back to input res
                self.up = nn.Conv2DTranspose(
                    n_class, 4, strides=2, padding=1,
                    weight_initializer=mx.init.Bilinear(),
                    use_bias=False)

        def hybrid_forward(self, F, x):
            return self.up(self.score(self.encoder(x)))

    net = FCN()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    L = gluon.loss.SoftmaxCrossEntropyLoss(axis=1)
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})
    xs, ys = nd.array(X), nd.array(Y)
    batch = 16
    for _ in range(args.epochs):
        for i in range(0, len(X), batch):
            xb, yb = xs[i:i + batch], ys[i:i + batch]
            with autograd.record():
                loss = L(net(xb), yb)
            loss.backward()
            trainer.step(xb.shape[0])

    pred = net(xs).asnumpy().argmax(axis=1)
    pixel_acc = float((pred == Y).mean())
    majority = float(max((Y == c).mean() for c in range(n_class)))
    print('fcn pixel accuracy %.3f (majority baseline %.3f)'
          % (pixel_acc, majority))
    return pixel_acc, majority


if __name__ == '__main__':
    main()
