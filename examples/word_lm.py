"""Word-level LSTM language model — the Gluon RNN workload
(reference: example/gluon/word_language_model/train.py and
example/rnn/word_lm/). Truncated-BPTT training with hidden-state
carry-over, gradient clipping, and Perplexity evaluation. Synthetic
Markov-chain text stands in for PTB in zero-egress environments.
"""
from __future__ import annotations

import argparse

# shared standalone-run bootstrap (repo root onto sys.path); when
# imported as examples.* the root is already importable and the
# script dir is not on sys.path, so gate on standalone execution
if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def synthetic_corpus(vocab, length, seed=0):
    """A first-order Markov chain: learnable structure so perplexity
    visibly drops below the uniform-vocab baseline."""
    rs = np.random.RandomState(seed)
    trans = rs.dirichlet(np.full(vocab, 0.1), size=vocab)
    toks = np.empty(length, dtype=np.int64)
    toks[0] = rs.randint(vocab)
    for i in range(1, length):
        toks[i] = rs.choice(vocab, p=trans[toks[i - 1]])
    return toks


def batchify(tokens, batch_size):
    n = len(tokens) // batch_size
    return tokens[:n * batch_size].reshape(batch_size, n).T  # (T, B)


class RNNModel:
    def __init__(self, mx, vocab, embed=64, hidden=128, layers=1,
                 dropout=0.2):
        from mxnet_tpu.gluon import nn, rnn
        self.net = nn.HybridSequential()
        with self.net.name_scope():
            self.embedding = nn.Embedding(vocab, embed)
            self.lstm = rnn.LSTM(hidden, num_layers=layers,
                                 dropout=dropout)
            self.decoder = nn.Dense(vocab, flatten=False)
        self.net.add(self.embedding, self.lstm, self.decoder)

    def __call__(self, data, hidden):
        emb = self.embedding(data)
        out, hidden = self.lstm(emb, hidden)
        return self.decoder(out), hidden


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--vocab', type=int, default=50)
    p.add_argument('--corpus-len', type=int, default=4000)
    p.add_argument('--batch-size', type=int, default=16)
    p.add_argument('--bptt', type=int, default=8)
    p.add_argument('--epochs', type=int, default=2)
    p.add_argument('--lr', type=float, default=1.0)
    p.add_argument('--clip', type=float, default=0.25)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    data = batchify(synthetic_corpus(args.vocab, args.corpus_len),
                    args.batch_size)
    model = RNNModel(mx, args.vocab)
    model.net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(model.net.collect_params(), 'sgd',
                            {'learning_rate': args.lr})
    L = gluon.loss.SoftmaxCrossEntropyLoss()

    ppl = None
    for epoch in range(args.epochs):
        hidden = model.lstm.begin_state(batch_size=args.batch_size)
        total, count = 0.0, 0
        for i in range(0, data.shape[0] - 1 - args.bptt, args.bptt):
            x = nd.array(data[i:i + args.bptt])
            y = nd.array(data[i + 1:i + 1 + args.bptt])
            # detach the carried state: truncated BPTT
            hidden = [h.detach() for h in hidden]
            with autograd.record():
                out, hidden = model(x, hidden)
                loss = L(out.reshape((-1, args.vocab)),
                         y.reshape((-1,)))
            loss.backward()
            # clip the global grad norm before the update
            grads = [p.grad() for p in
                     model.net.collect_params().values()
                     if p.grad_req != 'null']
            gluon.utils.clip_global_norm(
                grads, args.clip * args.batch_size * args.bptt)
            trainer.step(args.batch_size * args.bptt)
            total += float(loss.sum().asscalar())
            count += loss.size
        ppl = float(np.exp(total / count))
        print('epoch %d perplexity %.2f' % (epoch, ppl))
    assert ppl < args.vocab, 'model should beat the uniform baseline'
    return ppl


if __name__ == '__main__':
    main()
