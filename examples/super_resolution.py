"""Sub-pixel super-resolution (ESPCN) — upsampling via depth_to_space
(reference: example/gluon/super_resolution.py, which uses the same
PixelShuffle trick). Trains 2x upscaling on synthetic band-limited
images; reports PSNR gain over bicubic-free nearest-neighbour baseline.
"""
from __future__ import annotations

import argparse

# shared standalone-run bootstrap (repo root onto sys.path); when
# imported as examples.* the root is already importable and the
# script dir is not on sys.path, so gate on standalone execution
if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def smooth_images(rs, n, size):
    """Band-limited random images: low-frequency sinusoid mixtures."""
    xs = np.zeros((n, 1, size, size), dtype=np.float32)
    yy, xx = np.mgrid[0:size, 0:size] / size
    for i in range(n):
        img = np.zeros((size, size), np.float32)
        for _ in range(4):
            fx, fy = rs.uniform(0.5, 3, 2)
            ph = rs.uniform(0, 2 * np.pi, 2)
            img += rs.uniform(0.3, 1.0) * \
                np.sin(2 * np.pi * fx * xx + ph[0]) * \
                np.sin(2 * np.pi * fy * yy + ph[1])
        img = (img - img.min()) / (img.max() - img.min() + 1e-9)
        xs[i, 0] = img
    return xs


def psnr(a, b):
    mse = float(((a - b) ** 2).mean())
    return 10 * np.log10(1.0 / max(mse, 1e-12))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--upscale', type=int, default=2)
    p.add_argument('--size', type=int, default=32)
    p.add_argument('--num-samples', type=int, default=256)
    p.add_argument('--batch-size', type=int, default=16)
    p.add_argument('--epochs', type=int, default=10)
    p.add_argument('--lr', type=float, default=0.001)
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn

    r = args.upscale

    class SuperRes(nn.HybridBlock):
        """ESPCN: conv stack -> r^2 channels -> depth_to_space."""

        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.conv1 = nn.Conv2D(32, 5, padding=2,
                                       activation='relu')
                self.conv2 = nn.Conv2D(16, 3, padding=1,
                                       activation='relu')
                self.conv3 = nn.Conv2D(r * r, 3, padding=1)

        def hybrid_forward(self, F, x):
            x = self.conv3(self.conv2(self.conv1(x)))
            return F.depth_to_space(x, block_size=r)

    rs = np.random.RandomState(0)
    hi = smooth_images(rs, args.num_samples, args.size)
    lo = hi[:, :, ::r, ::r]   # decimated input

    net = SuperRes()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})
    L = gluon.loss.L2Loss()

    for epoch in range(args.epochs):
        order = rs.permutation(args.num_samples)
        tot = cnt = 0
        for b in range(0, args.num_samples, args.batch_size):
            idx = order[b:b + args.batch_size]
            xb, yb = nd.array(lo[idx]), nd.array(hi[idx])
            with autograd.record():
                loss = L(net(xb), yb)
            loss.backward()
            trainer.step(len(idx))
            tot += float(loss.mean().asscalar())
            cnt += 1
        print('epoch %d loss %.5f' % (epoch, tot / cnt))

    out = net(nd.array(lo)).asnumpy()
    model_psnr = psnr(out, hi)
    nearest = np.repeat(np.repeat(lo, r, axis=2), r, axis=3)
    base_psnr = psnr(nearest, hi)
    print('PSNR: model %.2f dB vs nearest-neighbour %.2f dB'
          % (model_psnr, base_psnr))
    assert model_psnr > base_psnr, 'training should beat nearest-neighbour'
    return model_psnr, base_psnr


if __name__ == '__main__':
    main()
