"""Capsule network (reference: example/capsnet — primary capsules +
dynamic routing-by-agreement + margin loss on MNIST-like digits). Tiny
TPU-native rendition: the routing iterations are a fixed-length Python
loop over pure ops (unrolled by XLA — no data-dependent control flow),
capsule affine votes are one batched matmul on the MXU, and squash /
softmax stay fused elementwise. Returns (accuracy, chance).
"""
from __future__ import annotations

import argparse

if not __package__:
    import _bootstrap  # noqa: F401

import numpy as np


def _digits(rs, n, size, n_class):
    """Blocky synthetic 'digits': class k = k+1 bright bars."""
    x = rs.rand(n, 1, size, size).astype('float32') * 0.1
    y = rs.randint(0, n_class, n)
    for i in range(n):
        for b in range(y[i] + 1):
            r = 2 + (b * (size - 4)) // max(n_class, 1)
            x[i, 0, r:r + 2, 2:size - 2] += 0.9
    return x, y.astype('float32')


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=10)
    p.add_argument('--num-samples', type=int, default=96)
    p.add_argument('--size', type=int, default=16)
    p.add_argument('--classes', type=int, default=4)
    p.add_argument('--routing-iters', type=int, default=2)
    p.add_argument('--lr', type=float, default=0.003)
    args = p.parse_args(argv)
    if args.routing_iters < 1:
        p.error('--routing-iters must be >= 1 (routing defines the '
                'class capsules)')

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn

    np.random.seed(0)
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    X, Y = _digits(rs, args.num_samples, args.size, args.classes)
    n_class = args.classes
    prim_caps, prim_dim, out_dim = 8, 4, 8

    class CapsNet(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.conv = nn.Conv2D(16, 5, strides=2, activation='relu')
                # primary capsules: one conv producing caps*dim channels
                self.primary = nn.Conv2D(prim_caps * prim_dim, 3,
                                         strides=2)
                # routing votes: (n_caps_in*prim_dim) -> class capsules
                self.votes = nn.Dense(n_class * out_dim * prim_caps,
                                      flatten=False)

        @staticmethod
        def _squash(F, v, axis):
            sq = F.sum(v * v, axis=axis, keepdims=True)
            return v * sq / (1.0 + sq) / F.sqrt(sq + 1e-9)

        def hybrid_forward(self, F, x):
            feats = self.primary(self.conv(x))          # (B, C*D, H, W)
            B = feats.shape[0]
            hw = feats.shape[2] * feats.shape[3]
            prim = feats.reshape((B, prim_caps, prim_dim, hw)) \
                .transpose((0, 3, 1, 2)).reshape((B, -1, prim_dim))
            prim = self._squash(F, prim, axis=-1)       # (B, N, D)
            n_in = prim.shape[1]
            # votes u_hat: every input capsule votes for every class
            u = self.votes(prim.reshape((B * n_in // prim_caps,
                                         prim_caps * prim_dim)))
            u = u.reshape((B, n_in // prim_caps, prim_caps, n_class,
                           out_dim)).reshape((B, -1, n_class, out_dim))
            # routing by agreement (fixed iterations, XLA-unrolled)
            b_logit = F.zeros((B, u.shape[1], n_class))
            for _ in range(args.routing_iters):
                c = F.softmax(b_logit, axis=-1)         # coupling
                s = F.sum(F.expand_dims(c, axis=-1) * u, axis=1)
                v = self._squash(F, s, axis=-1)         # (B, K, out)
                b_logit = b_logit + F.sum(
                    u * F.expand_dims(v, axis=1), axis=-1)
            return F.sqrt(F.sum(v * v, axis=-1) + 1e-9)  # class lengths

    net = CapsNet()
    net.initialize(mx.init.Xavier())

    def margin_loss(lengths, labels):
        onehot = nd.one_hot(labels, depth=n_class)
        pos = nd.maximum(0.9 - lengths, 0.0) ** 2
        neg = nd.maximum(lengths - 0.1, 0.0) ** 2
        return (onehot * pos + 0.5 * (1 - onehot) * neg).sum(axis=1)

    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})
    xs, ys = nd.array(X), nd.array(Y)
    split = args.num_samples * 3 // 4
    batch = 24
    for _ in range(args.epochs):
        for i in range(0, split, batch):
            xb, yb = xs[i:i + batch], ys[i:i + batch]
            with autograd.record():
                loss = margin_loss(net(xb), yb)
            loss.backward()
            trainer.step(xb.shape[0])

    pred = net(xs[split:]).asnumpy().argmax(axis=1)
    acc = float((pred == Y[split:]).mean())
    print('capsnet accuracy %.3f (chance %.3f, routing iters %d)'
          % (acc, 1.0 / n_class, args.routing_iters))
    return acc, 1.0 / n_class


if __name__ == '__main__':
    main()
