// Core C API — the MXNDArray* / MXSymbol* / MXKVStore* / profiler
// families (reference: include/mxnet/c_api.h, 207 functions;
// implementation src/c_api/c_api.cc). This library exports the
// high-traffic subset other-language bindings actually need: array
// create/shape/dtype/copy/save/load, symbol JSON round-trip and name
// listing, kvstore init/push/pull, profiler state + aggregate dump.
//
// Same embedding pattern as c_predict_api.cc: the runtime IS
// Python/XLA, so each entry point takes the GIL and calls
// mxnet_tpu.native.c_api_bridge; handles are PyObject pointers.

#include <Python.h>

#include <cstring>
#include <mutex>
#include <string>
#include <vector>

extern "C" {
typedef void* NDArrayHandle;
typedef void* SymbolHandle;
typedef void* KVStoreHandle;

int MXGetVersion(int* out);
const char* MXGetLastError();
int mxcapi_abi_version();

int MXNDArrayCreateEx(const unsigned* shape, unsigned ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle* out);
int MXNDArrayFree(NDArrayHandle handle);
int MXNDArrayGetShape(NDArrayHandle handle, unsigned* out_dim,
                      const unsigned** out_pdata);
int MXNDArrayGetDType(NDArrayHandle handle, int* out_dtype);
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                             size_t size);
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data, size_t size);
int MXNDArrayWaitAll();
int MXNDArraySave(const char* fname, unsigned num_args,
                  NDArrayHandle* args, const char** keys);
int MXNDArrayLoad(const char* fname, unsigned* out_size,
                  NDArrayHandle** out_arr, unsigned* out_name_size,
                  const char*** out_names);

int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out);
int MXSymbolSaveToJSON(SymbolHandle handle, const char** out_json);
int MXSymbolListArguments(SymbolHandle handle, unsigned* out_size,
                          const char*** out_array);
int MXSymbolListOutputs(SymbolHandle handle, unsigned* out_size,
                        const char*** out_array);
int MXSymbolListAuxiliaryStates(SymbolHandle handle, unsigned* out_size,
                                const char*** out_array);
int MXSymbolFree(SymbolHandle handle);

int MXKVStoreCreate(const char* type, KVStoreHandle* out);
int MXKVStoreFree(KVStoreHandle handle);
int MXKVStoreInit(KVStoreHandle handle, unsigned num, const int* keys,
                  NDArrayHandle* vals);
int MXKVStorePush(KVStoreHandle handle, unsigned num, const int* keys,
                  NDArrayHandle* vals, int priority);
int MXKVStorePull(KVStoreHandle handle, unsigned num, const int* keys,
                  NDArrayHandle* vals, int priority);

int MXSetProfilerState(int state);
int MXAggregateProfileStatsPrint(const char** out_str, int reset);
}

namespace {

thread_local std::string g_last_error;

// per-thread backing stores for pointers returned to C callers — valid
// until the next call that refills them on the same thread (the
// reference uses per-thread return stores the same way, c_api.h docs)
struct ReturnStore {
  std::vector<unsigned> shape;
  std::vector<std::string> strings;
  std::vector<const char*> cstrs;
  std::vector<void*> handles;
  std::string text;
};
thread_local ReturnStore g_ret;

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error = "python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c) g_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

void ensure_python() {
  static std::once_flag once;
  std::call_once(once, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
#if PY_VERSION_HEX < 0x03090000
      PyEval_InitThreads();
#endif
      PyEval_SaveThread();
    }
  });
}

PyObject* bridge() {
  static PyObject* mod = nullptr;
  if (!mod) mod = PyImport_ImportModule("mxnet_tpu.native.c_api_bridge");
  return mod;
}

struct Gil {
  PyGILState_STATE st;
  Gil() { st = PyGILState_Ensure(); }
  ~Gil() { PyGILState_Release(st); }
};

// call bridge.<fn>(*args); returns new reference or null (error set)
PyObject* call(const char* fn, PyObject* args) {
  PyObject* mod = bridge();
  if (!mod) return nullptr;
  PyObject* f = PyObject_GetAttrString(mod, fn);
  if (!f) return nullptr;
  PyObject* out = PyObject_CallObject(f, args);
  Py_DECREF(f);
  return out;
}

int fill_strings(PyObject* list, unsigned* out_size,
                 const char*** out_array) {
  g_ret.strings.clear();
  g_ret.cstrs.clear();
  Py_ssize_t n = PyList_Size(list);
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* c = PyUnicode_AsUTF8(PyList_GetItem(list, i));
    if (!c) return -1;
    g_ret.strings.emplace_back(c);
  }
  for (auto& s : g_ret.strings) g_ret.cstrs.push_back(s.c_str());
  *out_size = static_cast<unsigned>(n);
  *out_array = g_ret.cstrs.data();
  return 0;
}

}  // namespace

extern "C" {

int mxcapi_abi_version() { return 3; }

int MXGetVersion(int* out) {
  *out = 10600;  // 1.6.0-compatible surface
  return 0;
}

const char* MXGetLastError() { return g_last_error.c_str(); }

// -- NDArray ---------------------------------------------------------------

int MXNDArrayCreateEx(const unsigned* shape, unsigned ndim, int dev_type,
                      int dev_id, int /*delay_alloc*/, int dtype,
                      NDArrayHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* pyshape = PyTuple_New(ndim);
  for (unsigned i = 0; i < ndim; ++i)
    PyTuple_SetItem(pyshape, i, PyLong_FromUnsignedLong(shape[i]));
  PyObject* args = Py_BuildValue("(Oiii)", pyshape, dev_type, dev_id,
                                 dtype);
  Py_DECREF(pyshape);
  PyObject* arr = call("ndarray_create", args);
  Py_DECREF(args);
  if (!arr) { set_error_from_python(); return -1; }
  *out = arr;  // ownership transferred to the handle
  return 0;
}

int MXNDArrayFree(NDArrayHandle handle) {
  if (!handle) return 0;
  Gil gil;
  Py_DECREF(reinterpret_cast<PyObject*>(handle));
  return 0;
}

int MXNDArrayGetShape(NDArrayHandle handle, unsigned* out_dim,
                      const unsigned** out_pdata) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  PyObject* lst = call("ndarray_shape", args);
  Py_DECREF(args);
  if (!lst) { set_error_from_python(); return -1; }
  g_ret.shape.clear();
  Py_ssize_t n = PyList_Size(lst);
  for (Py_ssize_t i = 0; i < n; ++i)
    g_ret.shape.push_back(static_cast<unsigned>(
        PyLong_AsUnsignedLong(PyList_GetItem(lst, i))));
  Py_DECREF(lst);
  *out_dim = static_cast<unsigned>(n);
  *out_pdata = g_ret.shape.data();
  return 0;
}

int MXNDArrayGetDType(NDArrayHandle handle, int* out_dtype) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  PyObject* code = call("ndarray_dtype_code", args);
  Py_DECREF(args);
  if (!code) { set_error_from_python(); return -1; }
  *out_dtype = static_cast<int>(PyLong_AsLong(code));
  Py_DECREF(code);
  return 0;
}

// bytes-per-element straight from the array's dtype (no local table
// that could drift from the Python-side TypeFlag map)
static long element_size(PyObject* arr) {
  PyObject* args = Py_BuildValue("(O)", arr);
  PyObject* itemsize = call("ndarray_itemsize", args);
  Py_DECREF(args);
  if (!itemsize) return -1;
  long v = PyLong_AsLong(itemsize);
  Py_DECREF(itemsize);
  return v;
}

int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                             size_t size) {
  Gil gil;
  // size is an ELEMENT count (reference semantics); bridge validates
  PyObject* arr = reinterpret_cast<PyObject*>(handle);
  long itemsize = element_size(arr);
  if (itemsize < 0) { set_error_from_python(); return -1; }
  size_t nbytes = size * static_cast<size_t>(itemsize);
  PyObject* buf = PyBytes_FromStringAndSize(
      static_cast<const char*>(data), static_cast<Py_ssize_t>(nbytes));
  PyObject* args = Py_BuildValue("(OO)", arr, buf);
  Py_DECREF(buf);
  PyObject* r = call("ndarray_copy_from", args);
  Py_DECREF(args);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data, size_t size) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  PyObject* bytes = call("ndarray_copy_to", args);
  Py_DECREF(args);
  if (!bytes) { set_error_from_python(); return -1; }
  char* src = nullptr;
  Py_ssize_t nbytes = 0;
  if (PyBytes_AsStringAndSize(bytes, &src, &nbytes) != 0 || src == nullptr) {
    PyErr_Clear();
    g_last_error = "MXNDArraySyncCopyToCPU: bridge returned non-bytes";
    Py_DECREF(bytes);
    return -1;
  }
  // `size` is an element count and must match the array exactly
  // (reference semantics) — never overrun the caller's buffer
  long itemsize = element_size(reinterpret_cast<PyObject*>(handle));
  if (itemsize < 0) {
    set_error_from_python();
    Py_DECREF(bytes);
    return -1;
  }
  size_t want = size * static_cast<size_t>(itemsize);
  if (want != static_cast<size_t>(nbytes)) {
    g_last_error = "MXNDArraySyncCopyToCPU: size mismatch";
    Py_DECREF(bytes);
    return -1;
  }
  std::memcpy(data, src, nbytes);
  Py_DECREF(bytes);
  return 0;
}

int MXNDArrayWaitAll() {
  ensure_python();
  Gil gil;
  PyObject* r = call("ndarray_waitall", nullptr);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

int MXNDArraySave(const char* fname, unsigned num_args,
                  NDArrayHandle* args_in, const char** keys) {
  Gil gil;
  PyObject* arrs = PyList_New(num_args);
  for (unsigned i = 0; i < num_args; ++i) {
    PyObject* o = reinterpret_cast<PyObject*>(args_in[i]);
    Py_INCREF(o);
    PyList_SetItem(arrs, i, o);
  }
  PyObject* names;
  if (keys) {
    names = PyList_New(num_args);
    for (unsigned i = 0; i < num_args; ++i)
      PyList_SetItem(names, i, PyUnicode_FromString(keys[i]));
  } else {
    names = PyList_New(0);
  }
  PyObject* args = Py_BuildValue("(sOO)", fname, arrs, names);
  Py_DECREF(arrs);
  Py_DECREF(names);
  PyObject* r = call("ndarray_save", args);
  Py_DECREF(args);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

int MXNDArrayLoad(const char* fname, unsigned* out_size,
                  NDArrayHandle** out_arr, unsigned* out_name_size,
                  const char*** out_names) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", fname);
  PyObject* pair = call("ndarray_load", args);
  Py_DECREF(args);
  if (!pair) { set_error_from_python(); return -1; }
  PyObject* arrs = PyTuple_GetItem(pair, 0);
  PyObject* names = PyTuple_GetItem(pair, 1);
  g_ret.handles.clear();
  Py_ssize_t n = PyList_Size(arrs);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GetItem(arrs, i);
    Py_INCREF(o);  // handles own a reference; caller frees each
    g_ret.handles.push_back(o);
  }
  *out_size = static_cast<unsigned>(n);
  *out_arr = g_ret.handles.data();
  if (fill_strings(names, out_name_size, out_names) != 0) {
    set_error_from_python();
    Py_DECREF(pair);
    return -1;
  }
  Py_DECREF(pair);
  return 0;
}

// -- Symbol ----------------------------------------------------------------

int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", json);
  PyObject* sym = call("symbol_from_json", args);
  Py_DECREF(args);
  if (!sym) { set_error_from_python(); return -1; }
  *out = sym;
  return 0;
}

int MXSymbolSaveToJSON(SymbolHandle handle, const char** out_json) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  PyObject* s = call("symbol_to_json", args);
  Py_DECREF(args);
  if (!s) { set_error_from_python(); return -1; }
  const char* c = PyUnicode_AsUTF8(s);
  g_ret.text = c ? c : "";
  Py_DECREF(s);
  *out_json = g_ret.text.c_str();
  return 0;
}

static int list_names(SymbolHandle handle, const char* fn,
                      unsigned* out_size, const char*** out_array) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  PyObject* lst = call(fn, args);
  Py_DECREF(args);
  if (!lst) { set_error_from_python(); return -1; }
  int rc = fill_strings(lst, out_size, out_array);
  Py_DECREF(lst);
  if (rc) set_error_from_python();
  return rc;
}

int MXSymbolListArguments(SymbolHandle handle, unsigned* out_size,
                          const char*** out_array) {
  return list_names(handle, "symbol_list_arguments", out_size, out_array);
}

int MXSymbolListOutputs(SymbolHandle handle, unsigned* out_size,
                        const char*** out_array) {
  return list_names(handle, "symbol_list_outputs", out_size, out_array);
}

int MXSymbolListAuxiliaryStates(SymbolHandle handle, unsigned* out_size,
                                const char*** out_array) {
  return list_names(handle, "symbol_list_aux", out_size, out_array);
}

int MXSymbolFree(SymbolHandle handle) { return MXNDArrayFree(handle); }

// -- KVStore ---------------------------------------------------------------

int MXKVStoreCreate(const char* type, KVStoreHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", type);
  PyObject* kv = call("kvstore_create", args);
  Py_DECREF(args);
  if (!kv) { set_error_from_python(); return -1; }
  *out = kv;
  return 0;
}

int MXKVStoreFree(KVStoreHandle handle) { return MXNDArrayFree(handle); }

static int kv_op(const char* fn, KVStoreHandle handle, unsigned num,
                 const int* keys, NDArrayHandle* vals) {
  Gil gil;
  PyObject* pykeys = PyList_New(num);
  PyObject* pyvals = PyList_New(num);
  for (unsigned i = 0; i < num; ++i) {
    PyList_SetItem(pykeys, i, PyLong_FromLong(keys[i]));
    PyObject* o = reinterpret_cast<PyObject*>(vals[i]);
    Py_INCREF(o);
    PyList_SetItem(pyvals, i, o);
  }
  PyObject* args = Py_BuildValue(
      "(OOO)", reinterpret_cast<PyObject*>(handle), pykeys, pyvals);
  Py_DECREF(pykeys);
  Py_DECREF(pyvals);
  PyObject* r = call(fn, args);
  Py_DECREF(args);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

int MXKVStoreInit(KVStoreHandle handle, unsigned num, const int* keys,
                  NDArrayHandle* vals) {
  return kv_op("kvstore_init", handle, num, keys, vals);
}

int MXKVStorePush(KVStoreHandle handle, unsigned num, const int* keys,
                  NDArrayHandle* vals, int /*priority*/) {
  return kv_op("kvstore_push", handle, num, keys, vals);
}

int MXKVStorePull(KVStoreHandle handle, unsigned num, const int* keys,
                  NDArrayHandle* vals, int /*priority*/) {
  return kv_op("kvstore_pull", handle, num, keys, vals);
}

// -- Profiler --------------------------------------------------------------

int MXSetProfilerState(int state) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", state);
  PyObject* r = call("profiler_set_state", args);
  Py_DECREF(args);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

int MXAggregateProfileStatsPrint(const char** out_str, int reset) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", reset);
  PyObject* s = call("profiler_dumps", args);
  Py_DECREF(args);
  if (!s) { set_error_from_python(); return -1; }
  const char* c = PyUnicode_AsUTF8(s);
  g_ret.text = c ? c : "";
  Py_DECREF(s);
  *out_str = g_ret.text.c_str();
  return 0;
}

}  // extern "C"
