// Core C API — the MXNDArray* / MXSymbol* / MXKVStore* / profiler
// families (reference: include/mxnet/c_api.h, 207 functions;
// implementation src/c_api/c_api.cc). This library exports the
// high-traffic subset other-language bindings actually need: array
// create/shape/dtype/copy/save/load, symbol JSON round-trip and name
// listing, kvstore init/push/pull, profiler state + aggregate dump.
//
// Same embedding pattern as c_predict_api.cc: the runtime IS
// Python/XLA, so each entry point takes the GIL and calls
// mxnet_tpu.native.c_api_bridge; handles are PyObject pointers.

#include <Python.h>

#include <cstring>
#include <mutex>
#include <string>
#include <vector>

extern "C" {
typedef void* NDArrayHandle;
typedef void* SymbolHandle;
typedef void* KVStoreHandle;

int MXGetVersion(int* out);
const char* MXGetLastError();
int mxcapi_abi_version();

int MXNDArrayCreateEx(const unsigned* shape, unsigned ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle* out);
int MXNDArrayFree(NDArrayHandle handle);
int MXNDArrayGetShape(NDArrayHandle handle, unsigned* out_dim,
                      const unsigned** out_pdata);
int MXNDArrayGetDType(NDArrayHandle handle, int* out_dtype);
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                             size_t size);
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data, size_t size);
int MXNDArrayWaitAll();
int MXNDArraySave(const char* fname, unsigned num_args,
                  NDArrayHandle* args, const char** keys);
int MXNDArrayLoad(const char* fname, unsigned* out_size,
                  NDArrayHandle** out_arr, unsigned* out_name_size,
                  const char*** out_names);

int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out);
int MXSymbolSaveToJSON(SymbolHandle handle, const char** out_json);
int MXSymbolListArguments(SymbolHandle handle, unsigned* out_size,
                          const char*** out_array);
int MXSymbolListOutputs(SymbolHandle handle, unsigned* out_size,
                        const char*** out_array);
int MXSymbolListAuxiliaryStates(SymbolHandle handle, unsigned* out_size,
                                const char*** out_array);
int MXSymbolFree(SymbolHandle handle);

int MXKVStoreCreate(const char* type, KVStoreHandle* out);
int MXKVStoreFree(KVStoreHandle handle);
int MXKVStoreInit(KVStoreHandle handle, unsigned num, const int* keys,
                  NDArrayHandle* vals);
int MXKVStorePush(KVStoreHandle handle, unsigned num, const int* keys,
                  NDArrayHandle* vals, int priority);
int MXKVStorePull(KVStoreHandle handle, unsigned num, const int* keys,
                  NDArrayHandle* vals, int priority);

int MXSetProfilerState(int state);
int MXAggregateProfileStatsPrint(const char** out_str, int reset);
}

namespace {

thread_local std::string g_last_error;

// per-thread backing stores for pointers returned to C callers — valid
// until the next call that refills them on the same thread (the
// reference uses per-thread return stores the same way, c_api.h docs)
struct ReturnStore {
  std::vector<unsigned> shape;
  std::vector<std::string> strings;
  std::vector<const char*> cstrs;
  std::vector<void*> handles;
  std::string text;
  // shape-inference triple-pointer backing (in/out/aux groups)
  std::vector<std::vector<unsigned>> sbufs;
  std::vector<unsigned> ndims[3];
  std::vector<const unsigned*> sptrs[3];
  std::vector<unsigned long long> idx64;
  std::vector<int> ints;
  std::vector<void*> handles2;
  std::vector<void*> handles3;
};
thread_local ReturnStore g_ret;

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error = "python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c) g_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

void ensure_python() {
  static std::once_flag once;
  std::call_once(once, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
#if PY_VERSION_HEX < 0x03090000
      PyEval_InitThreads();
#endif
      PyEval_SaveThread();
    }
  });
}

PyObject* bridge() {
  static PyObject* mod = nullptr;
  if (!mod) mod = PyImport_ImportModule("mxnet_tpu.native.c_api_bridge");
  return mod;
}

struct Gil {
  PyGILState_STATE st;
  // every entry point may be the process's first call: initialize the
  // embedded interpreter before touching the GIL (idempotent)
  Gil() { ensure_python(); st = PyGILState_Ensure(); }
  ~Gil() { PyGILState_Release(st); }
};

// call bridge.<fn>(*args); returns new reference or null (error set)
PyObject* call(const char* fn, PyObject* args) {
  PyObject* mod = bridge();
  if (!mod) return nullptr;
  PyObject* f = PyObject_GetAttrString(mod, fn);
  if (!f) return nullptr;
  PyObject* out = PyObject_CallObject(f, args);
  Py_DECREF(f);
  return out;
}

int fill_strings(PyObject* list, unsigned* out_size,
                 const char*** out_array) {
  g_ret.strings.clear();
  g_ret.cstrs.clear();
  Py_ssize_t n = PyList_Size(list);
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* c = PyUnicode_AsUTF8(PyList_GetItem(list, i));
    if (!c) return -1;
    g_ret.strings.emplace_back(c);
  }
  for (auto& s : g_ret.strings) g_ret.cstrs.push_back(s.c_str());
  *out_size = static_cast<unsigned>(n);
  *out_array = g_ret.cstrs.data();
  return 0;
}


PyObject* make_str_list(unsigned n, const char* const* arr) {
  PyObject* lst = PyList_New(n);
  for (unsigned i = 0; i < n; ++i)
    PyList_SetItem(lst, i, PyUnicode_FromString(arr && arr[i] ? arr[i] : ""));
  return lst;
}

PyObject* make_handle_list(unsigned n, void* const* arr) {
  PyObject* lst = PyList_New(n);
  for (unsigned i = 0; i < n; ++i) {
    PyObject* o = arr && arr[i] ? reinterpret_cast<PyObject*>(arr[i])
                                : Py_None;
    Py_INCREF(o);
    PyList_SetItem(lst, i, o);
  }
  return lst;
}

PyObject* make_uint_list(unsigned n, const unsigned* arr) {
  PyObject* lst = PyList_New(n);
  for (unsigned i = 0; i < n; ++i)
    PyList_SetItem(lst, i, PyLong_FromUnsignedLong(arr ? arr[i] : 0));
  return lst;
}

// run bridge fn, discard result; 0/-1 status
int simple(const char* fn, PyObject* args) {
  ensure_python();
  Gil gil;
  PyObject* r = call(fn, args);
  Py_XDECREF(args);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

int out_handle(const char* fn, PyObject* args, void** out) {
  ensure_python();
  Gil gil;
  PyObject* r = call(fn, args);
  Py_XDECREF(args);
  if (!r) { set_error_from_python(); return -1; }
  *out = r;  // ownership -> handle
  return 0;
}

int out_text(const char* fn, PyObject* args, const char** out) {
  ensure_python();
  Gil gil;
  PyObject* r = call(fn, args);
  Py_XDECREF(args);
  if (!r) { set_error_from_python(); return -1; }
  if (r == Py_None) {
    g_ret.text.clear();
    *out = nullptr;
  } else {
    const char* c = PyUnicode_AsUTF8(r);
    g_ret.text = c ? c : "";
    *out = g_ret.text.c_str();
  }
  Py_DECREF(r);
  return 0;
}

int out_long(const char* fn, PyObject* args, long* out) {
  ensure_python();
  Gil gil;
  PyObject* r = call(fn, args);
  Py_XDECREF(args);
  if (!r) { set_error_from_python(); return -1; }
  *out = PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

int out_handle_list(const char* fn, PyObject* args, int* num_out,
                    void*** outs) {
  ensure_python();
  Gil gil;
  PyObject* lst = call(fn, args);
  Py_XDECREF(args);
  if (!lst) { set_error_from_python(); return -1; }
  g_ret.handles.clear();
  Py_ssize_t n = PyList_Size(lst);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GetItem(lst, i);
    Py_INCREF(o);
    g_ret.handles.push_back(o);
  }
  Py_DECREF(lst);
  *num_out = static_cast<int>(n);
  *outs = g_ret.handles.data();
  return 0;
}

int out_str_list(const char* fn, PyObject* args, unsigned* out_size,
                 const char*** out_array) {
  ensure_python();
  Gil gil;
  PyObject* lst = call(fn, args);
  Py_XDECREF(args);
  if (!lst) { set_error_from_python(); return -1; }
  int rc = fill_strings(lst, out_size, out_array);
  Py_DECREF(lst);
  if (rc) set_error_from_python();
  return rc;
}

// kept alive forever: atomic-creator / data-iter creator handles.
// Returns the cached list (borrowed; owned by the cache dict).
PyObject* creator_list(const char* fn) {
  static PyObject* cache = nullptr;  // dict: fn -> list
  if (!cache) cache = PyDict_New();
  PyObject* lst = PyDict_GetItemString(cache, fn);
  if (!lst) {
    lst = call(fn, nullptr);
    if (!lst) return nullptr;
    PyDict_SetItemString(cache, fn, lst);
    Py_DECREF(lst);
    lst = PyDict_GetItemString(cache, fn);
  }
  return lst;
}

}  // namespace

extern "C" {

int mxcapi_abi_version() { return 4; }

int MXGetVersion(int* out) {
  *out = 10600;  // 1.6.0-compatible surface
  return 0;
}

const char* MXGetLastError() { return g_last_error.c_str(); }

// -- NDArray ---------------------------------------------------------------

int MXNDArrayCreateEx(const unsigned* shape, unsigned ndim, int dev_type,
                      int dev_id, int /*delay_alloc*/, int dtype,
                      NDArrayHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* pyshape = PyTuple_New(ndim);
  for (unsigned i = 0; i < ndim; ++i)
    PyTuple_SetItem(pyshape, i, PyLong_FromUnsignedLong(shape[i]));
  PyObject* args = Py_BuildValue("(Oiii)", pyshape, dev_type, dev_id,
                                 dtype);
  Py_DECREF(pyshape);
  PyObject* arr = call("ndarray_create", args);
  Py_DECREF(args);
  if (!arr) { set_error_from_python(); return -1; }
  *out = arr;  // ownership transferred to the handle
  return 0;
}

int MXNDArrayFree(NDArrayHandle handle) {
  if (!handle) return 0;
  Gil gil;
  Py_DECREF(reinterpret_cast<PyObject*>(handle));
  return 0;
}

int MXNDArrayGetShape(NDArrayHandle handle, unsigned* out_dim,
                      const unsigned** out_pdata) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  PyObject* lst = call("ndarray_shape", args);
  Py_DECREF(args);
  if (!lst) { set_error_from_python(); return -1; }
  g_ret.shape.clear();
  Py_ssize_t n = PyList_Size(lst);
  for (Py_ssize_t i = 0; i < n; ++i)
    g_ret.shape.push_back(static_cast<unsigned>(
        PyLong_AsUnsignedLong(PyList_GetItem(lst, i))));
  Py_DECREF(lst);
  *out_dim = static_cast<unsigned>(n);
  *out_pdata = g_ret.shape.data();
  return 0;
}

int MXNDArrayGetDType(NDArrayHandle handle, int* out_dtype) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  PyObject* code = call("ndarray_dtype_code", args);
  Py_DECREF(args);
  if (!code) { set_error_from_python(); return -1; }
  *out_dtype = static_cast<int>(PyLong_AsLong(code));
  Py_DECREF(code);
  return 0;
}

// bytes-per-element straight from the array's dtype (no local table
// that could drift from the Python-side TypeFlag map)
static long element_size(PyObject* arr) {
  PyObject* args = Py_BuildValue("(O)", arr);
  PyObject* itemsize = call("ndarray_itemsize", args);
  Py_DECREF(args);
  if (!itemsize) return -1;
  long v = PyLong_AsLong(itemsize);
  Py_DECREF(itemsize);
  return v;
}

int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                             size_t size) {
  Gil gil;
  // size is an ELEMENT count (reference semantics); bridge validates
  PyObject* arr = reinterpret_cast<PyObject*>(handle);
  long itemsize = element_size(arr);
  if (itemsize < 0) { set_error_from_python(); return -1; }
  size_t nbytes = size * static_cast<size_t>(itemsize);
  PyObject* buf = PyBytes_FromStringAndSize(
      static_cast<const char*>(data), static_cast<Py_ssize_t>(nbytes));
  PyObject* args = Py_BuildValue("(OO)", arr, buf);
  Py_DECREF(buf);
  PyObject* r = call("ndarray_copy_from", args);
  Py_DECREF(args);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data, size_t size) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  PyObject* bytes = call("ndarray_copy_to", args);
  Py_DECREF(args);
  if (!bytes) { set_error_from_python(); return -1; }
  char* src = nullptr;
  Py_ssize_t nbytes = 0;
  if (PyBytes_AsStringAndSize(bytes, &src, &nbytes) != 0 || src == nullptr) {
    PyErr_Clear();
    g_last_error = "MXNDArraySyncCopyToCPU: bridge returned non-bytes";
    Py_DECREF(bytes);
    return -1;
  }
  // `size` is an element count and must match the array exactly
  // (reference semantics) — never overrun the caller's buffer
  long itemsize = element_size(reinterpret_cast<PyObject*>(handle));
  if (itemsize < 0) {
    set_error_from_python();
    Py_DECREF(bytes);
    return -1;
  }
  size_t want = size * static_cast<size_t>(itemsize);
  if (want != static_cast<size_t>(nbytes)) {
    g_last_error = "MXNDArraySyncCopyToCPU: size mismatch";
    Py_DECREF(bytes);
    return -1;
  }
  std::memcpy(data, src, nbytes);
  Py_DECREF(bytes);
  return 0;
}

int MXNDArrayWaitAll() {
  ensure_python();
  Gil gil;
  PyObject* r = call("ndarray_waitall", nullptr);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

int MXNDArraySave(const char* fname, unsigned num_args,
                  NDArrayHandle* args_in, const char** keys) {
  Gil gil;
  PyObject* arrs = PyList_New(num_args);
  for (unsigned i = 0; i < num_args; ++i) {
    PyObject* o = reinterpret_cast<PyObject*>(args_in[i]);
    Py_INCREF(o);
    PyList_SetItem(arrs, i, o);
  }
  PyObject* names;
  if (keys) {
    names = PyList_New(num_args);
    for (unsigned i = 0; i < num_args; ++i)
      PyList_SetItem(names, i, PyUnicode_FromString(keys[i]));
  } else {
    names = PyList_New(0);
  }
  PyObject* args = Py_BuildValue("(sOO)", fname, arrs, names);
  Py_DECREF(arrs);
  Py_DECREF(names);
  PyObject* r = call("ndarray_save", args);
  Py_DECREF(args);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

int MXNDArrayLoad(const char* fname, unsigned* out_size,
                  NDArrayHandle** out_arr, unsigned* out_name_size,
                  const char*** out_names) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", fname);
  PyObject* pair = call("ndarray_load", args);
  Py_DECREF(args);
  if (!pair) { set_error_from_python(); return -1; }
  PyObject* arrs = PyTuple_GetItem(pair, 0);
  PyObject* names = PyTuple_GetItem(pair, 1);
  g_ret.handles.clear();
  Py_ssize_t n = PyList_Size(arrs);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GetItem(arrs, i);
    Py_INCREF(o);  // handles own a reference; caller frees each
    g_ret.handles.push_back(o);
  }
  *out_size = static_cast<unsigned>(n);
  *out_arr = g_ret.handles.data();
  if (fill_strings(names, out_name_size, out_names) != 0) {
    set_error_from_python();
    Py_DECREF(pair);
    return -1;
  }
  Py_DECREF(pair);
  return 0;
}

// -- Symbol ----------------------------------------------------------------

int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", json);
  PyObject* sym = call("symbol_from_json", args);
  Py_DECREF(args);
  if (!sym) { set_error_from_python(); return -1; }
  *out = sym;
  return 0;
}

int MXSymbolSaveToJSON(SymbolHandle handle, const char** out_json) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  PyObject* s = call("symbol_to_json", args);
  Py_DECREF(args);
  if (!s) { set_error_from_python(); return -1; }
  const char* c = PyUnicode_AsUTF8(s);
  g_ret.text = c ? c : "";
  Py_DECREF(s);
  *out_json = g_ret.text.c_str();
  return 0;
}

static int list_names(SymbolHandle handle, const char* fn,
                      unsigned* out_size, const char*** out_array) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  PyObject* lst = call(fn, args);
  Py_DECREF(args);
  if (!lst) { set_error_from_python(); return -1; }
  int rc = fill_strings(lst, out_size, out_array);
  Py_DECREF(lst);
  if (rc) set_error_from_python();
  return rc;
}

int MXSymbolListArguments(SymbolHandle handle, unsigned* out_size,
                          const char*** out_array) {
  return list_names(handle, "symbol_list_arguments", out_size, out_array);
}

int MXSymbolListOutputs(SymbolHandle handle, unsigned* out_size,
                        const char*** out_array) {
  return list_names(handle, "symbol_list_outputs", out_size, out_array);
}

int MXSymbolListAuxiliaryStates(SymbolHandle handle, unsigned* out_size,
                                const char*** out_array) {
  return list_names(handle, "symbol_list_aux", out_size, out_array);
}

int MXSymbolFree(SymbolHandle handle) { return MXNDArrayFree(handle); }

// -- KVStore ---------------------------------------------------------------

int MXKVStoreCreate(const char* type, KVStoreHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", type);
  PyObject* kv = call("kvstore_create", args);
  Py_DECREF(args);
  if (!kv) { set_error_from_python(); return -1; }
  *out = kv;
  return 0;
}

int MXKVStoreFree(KVStoreHandle handle) { return MXNDArrayFree(handle); }

static int kv_op(const char* fn, KVStoreHandle handle, unsigned num,
                 const int* keys, NDArrayHandle* vals) {
  Gil gil;
  PyObject* pykeys = PyList_New(num);
  PyObject* pyvals = PyList_New(num);
  for (unsigned i = 0; i < num; ++i) {
    PyList_SetItem(pykeys, i, PyLong_FromLong(keys[i]));
    PyObject* o = reinterpret_cast<PyObject*>(vals[i]);
    Py_INCREF(o);
    PyList_SetItem(pyvals, i, o);
  }
  PyObject* args = Py_BuildValue(
      "(OOO)", reinterpret_cast<PyObject*>(handle), pykeys, pyvals);
  Py_DECREF(pykeys);
  Py_DECREF(pyvals);
  PyObject* r = call(fn, args);
  Py_DECREF(args);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

int MXKVStoreInit(KVStoreHandle handle, unsigned num, const int* keys,
                  NDArrayHandle* vals) {
  return kv_op("kvstore_init", handle, num, keys, vals);
}

int MXKVStorePush(KVStoreHandle handle, unsigned num, const int* keys,
                  NDArrayHandle* vals, int /*priority*/) {
  return kv_op("kvstore_push", handle, num, keys, vals);
}

int MXKVStorePull(KVStoreHandle handle, unsigned num, const int* keys,
                  NDArrayHandle* vals, int /*priority*/) {
  return kv_op("kvstore_pull", handle, num, keys, vals);
}

// -- Profiler --------------------------------------------------------------

int MXSetProfilerState(int state) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", state);
  PyObject* r = call("profiler_set_state", args);
  Py_DECREF(args);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

int MXAggregateProfileStatsPrint(const char** out_str, int reset) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", reset);
  PyObject* s = call("profiler_dumps", args);
  Py_DECREF(args);
  if (!s) { set_error_from_python(); return -1; }
  const char* c = PyUnicode_AsUTF8(s);
  g_ret.text = c ? c : "";
  Py_DECREF(s);
  *out_str = g_ret.text.c_str();
  return 0;
}

}  // extern "C"

// ===========================================================================
// Round-4 breadth: NDArray extras, imperative invoke, autograd, symbol
// manipulation + inference, executors, cached ops, data iterators,
// kvstore metadata, recordio, profiler objects, misc runtime
// (reference: src/c_api/c_api_ndarray.cc, c_api_executor.cc,
// c_api_symbolic.cc, c_api.cc, c_api_profile.cc)
// ===========================================================================

extern "C" {

typedef unsigned mx_uint;
typedef void* ExecutorHandle;
typedef void* DataIterHandle;
typedef void* CachedOpHandle;
typedef void* AtomicSymbolCreator;
typedef void* DataIterCreator;
typedef void* RecordIOHandle;
typedef void* ProfileHandle;

// -- NDArray extras --------------------------------------------------------

int MXNDArrayCreate(const mx_uint* shape, mx_uint ndim, int dev_type,
                    int dev_id, int delay_alloc, NDArrayHandle* out) {
  // f32 is dtype code 0 (reference MXNDArrayCreate fixes f32)
  return MXNDArrayCreateEx(shape, ndim, dev_type, dev_id, delay_alloc, 0,
                           out);
}

int MXNDArrayCreateNone(NDArrayHandle* out) {
  return out_handle("ndarray_create_none", nullptr, out);
}

int MXNDArrayGetShapeEx(NDArrayHandle handle, int* out_dim,
                        const int** out_pdata) {
  unsigned dim = 0;
  const unsigned* pdata = nullptr;
  if (MXNDArrayGetShape(handle, &dim, &pdata) != 0) return -1;
  g_ret.ints.assign(pdata, pdata + dim);
  *out_dim = static_cast<int>(dim);
  *out_pdata = g_ret.ints.data();
  return 0;
}

int MXNDArraySlice(NDArrayHandle handle, mx_uint start, mx_uint stop,
                   NDArrayHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OII)",
                                 reinterpret_cast<PyObject*>(handle),
                                 start, stop);
  return out_handle("ndarray_slice", args, out);
}

int MXNDArrayAt(NDArrayHandle handle, mx_uint idx, NDArrayHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OI)",
                                 reinterpret_cast<PyObject*>(handle), idx);
  return out_handle("ndarray_at", args, out);
}

int MXNDArrayReshape(NDArrayHandle handle, int ndim, int* dims,
                     NDArrayHandle* out) {
  Gil gil;
  PyObject* pdims = PyList_New(ndim);
  for (int i = 0; i < ndim; ++i)
    PyList_SetItem(pdims, i, PyLong_FromLong(dims[i]));
  PyObject* args = Py_BuildValue("(OO)",
                                 reinterpret_cast<PyObject*>(handle), pdims);
  Py_DECREF(pdims);
  return out_handle("ndarray_reshape", args, out);
}

int MXNDArrayReshape64(NDArrayHandle handle, int ndim, long long* dims,
                       bool reverse, NDArrayHandle* out) {
  Gil gil;
  PyObject* pdims = PyList_New(ndim);
  for (int i = 0; i < ndim; ++i)
    PyList_SetItem(pdims, i, PyLong_FromLongLong(dims[i]));
  PyObject* args = Py_BuildValue(
      "(OOi)", reinterpret_cast<PyObject*>(handle), pdims,
      reverse ? 1 : 0);
  Py_DECREF(pdims);
  return out_handle("ndarray_reshape", args, out);
}

int MXNDArrayGetContext(NDArrayHandle handle, int* out_dev_type,
                        int* out_dev_id) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  PyObject* pair = call("ndarray_context", args);
  Py_DECREF(args);
  if (!pair) { set_error_from_python(); return -1; }
  *out_dev_type = (int)PyLong_AsLong(PyTuple_GetItem(pair, 0));
  *out_dev_id = (int)PyLong_AsLong(PyTuple_GetItem(pair, 1));
  Py_DECREF(pair);
  return 0;
}

int MXNDArrayGetStorageType(NDArrayHandle handle, int* out_stype) {
  Gil gil;
  long v = 0;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  if (out_long("ndarray_storage_type", args, &v) != 0) return -1;
  *out_stype = (int)v;
  return 0;
}

int MXNDArrayWaitToRead(NDArrayHandle handle) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  return simple("ndarray_wait_to_read", args);
}

int MXNDArrayWaitToWrite(NDArrayHandle handle) {
  return MXNDArrayWaitToRead(handle);
}

int MXNDArrayDetach(NDArrayHandle handle, NDArrayHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  return out_handle("ndarray_detach", args, out);
}

int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  return out_handle("ndarray_get_grad", args, out);
}

int MXNDArraySetGradState(NDArrayHandle handle, int state) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)",
                                 reinterpret_cast<PyObject*>(handle), state);
  return simple("ndarray_set_grad_state", args);
}

int MXNDArrayGetGradState(NDArrayHandle handle, int* out) {
  Gil gil;
  long v = 0;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  if (out_long("ndarray_get_grad_state", args, &v) != 0) return -1;
  *out = (int)v;
  return 0;
}

int MXNDArraySyncCopyFromNDArray(NDArrayHandle dst, NDArrayHandle src,
                                 int /*i*/) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OO)",
                                 reinterpret_cast<PyObject*>(dst),
                                 reinterpret_cast<PyObject*>(src));
  return simple("ndarray_copy_from_ndarray", args);
}

int MXNDArraySyncCheckFormat(NDArrayHandle handle, const bool full_check) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)",
                                 reinterpret_cast<PyObject*>(handle),
                                 full_check ? 1 : 0);
  return simple("ndarray_check_format", args);
}

int MXNDArraySaveRawBytes(NDArrayHandle handle, size_t* out_size,
                          const char** out_buf) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  PyObject* bytes = call("ndarray_save_raw_bytes", args);
  Py_DECREF(args);
  if (!bytes) { set_error_from_python(); return -1; }
  char* buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(bytes, &buf, &n) != 0) {
    PyErr_Clear();
    Py_DECREF(bytes);
    g_last_error = "raw-bytes bridge returned non-bytes";
    return -1;
  }
  g_ret.text.assign(buf, n);
  Py_DECREF(bytes);
  *out_size = (size_t)n;
  *out_buf = g_ret.text.data();
  return 0;
}

int MXNDArrayLoadFromRawBytes(const void* buf, size_t size,
                              NDArrayHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* b = PyBytes_FromStringAndSize((const char*)buf,
                                          (Py_ssize_t)size);
  PyObject* args = Py_BuildValue("(O)", b);
  Py_DECREF(b);
  return out_handle("ndarray_load_from_raw_bytes", args, out);
}

int MXNDArrayLoadFromBuffer(const void* buf, size_t size,
                            mx_uint* out_size, NDArrayHandle** out_arr,
                            mx_uint* out_name_size,
                            const char*** out_names) {
  ensure_python();
  Gil gil;
  PyObject* b = PyBytes_FromStringAndSize((const char*)buf,
                                          (Py_ssize_t)size);
  PyObject* args = Py_BuildValue("(O)", b);
  Py_DECREF(b);
  PyObject* pair = call("ndarray_load_from_buffer", args);
  Py_DECREF(args);
  if (!pair) { set_error_from_python(); return -1; }
  PyObject* arrs = PyTuple_GetItem(pair, 0);
  PyObject* names = PyTuple_GetItem(pair, 1);
  g_ret.handles.clear();
  Py_ssize_t n = PyList_Size(arrs);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GetItem(arrs, i);
    Py_INCREF(o);
    g_ret.handles.push_back(o);
  }
  *out_size = (mx_uint)n;
  *out_arr = g_ret.handles.data();
  int rc = fill_strings(names, out_name_size, out_names);
  Py_DECREF(pair);
  if (rc) set_error_from_python();
  return rc;
}

// -- op listing + imperative invoke ---------------------------------------

int MXListAllOpNames(mx_uint* out_size, const char*** out_array) {
  return out_str_list("list_all_op_names", nullptr, out_size, out_array);
}

int MXSymbolListAtomicSymbolCreators(mx_uint* out_size,
                                     AtomicSymbolCreator** out_array) {
  Gil gil;
  PyObject* lst = creator_list("list_atomic_creators");
  if (!lst) { set_error_from_python(); return -1; }
  Py_ssize_t n = PyList_Size(lst);
  g_ret.handles.clear();
  for (Py_ssize_t i = 0; i < n; ++i)
    g_ret.handles.push_back(PyList_GetItem(lst, i));  // cache keeps alive
  *out_size = (mx_uint)n;
  *out_array = g_ret.handles.data();
  return 0;
}

int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator creator,
                                const char** name) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(creator));
  return out_text("atomic_creator_name", args, name);
}

int MXSymbolGetAtomicSymbolInfo(
    AtomicSymbolCreator creator, const char** name, const char** description,
    mx_uint* num_args, const char*** arg_names, const char*** arg_type_infos,
    const char*** arg_descriptions, const char** key_var_num_args,
    const char** return_type) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(creator));
  PyObject* tup = call("atomic_creator_info", args);
  Py_DECREF(args);
  if (!tup) { set_error_from_python(); return -1; }
  g_ret.strings.clear();
  g_ret.cstrs.clear();
  const char* n0 = PyUnicode_AsUTF8(PyTuple_GetItem(tup, 0));
  const char* d0 = PyUnicode_AsUTF8(PyTuple_GetItem(tup, 1));
  const char* k0 = PyUnicode_AsUTF8(PyTuple_GetItem(tup, 2));
  PyObject* anames = PyTuple_GetItem(tup, 3);
  PyObject* atypes = PyTuple_GetItem(tup, 4);
  PyObject* adescs = PyTuple_GetItem(tup, 5);
  Py_ssize_t nargs = anames ? PyList_Size(anames) : 0;
  // reserve up-front: c_str()/data() pointers must stay stable below
  g_ret.strings.reserve(3 + 3 * (size_t)nargs);
  g_ret.cstrs.reserve(3 * (size_t)nargs);
  g_ret.strings.emplace_back(n0 ? n0 : "");
  g_ret.strings.emplace_back(d0 ? d0 : "");
  g_ret.strings.emplace_back(k0 ? k0 : "");
  for (int part = 0; part < 3; ++part) {
    PyObject* lst = part == 0 ? anames : (part == 1 ? atypes : adescs);
    for (Py_ssize_t i = 0; i < nargs; ++i) {
      const char* s = PyUnicode_AsUTF8(PyList_GetItem(lst, i));
      g_ret.strings.emplace_back(s ? s : "");
      g_ret.cstrs.push_back(g_ret.strings.back().c_str());
    }
  }
  Py_DECREF(tup);
  *name = g_ret.strings[0].c_str();
  *description = g_ret.strings[1].c_str();
  *key_var_num_args = g_ret.strings[2].c_str();
  *num_args = (mx_uint)nargs;
  if (arg_names)
    *arg_names = nargs ? &g_ret.cstrs[0] : nullptr;
  if (arg_type_infos)
    *arg_type_infos = nargs ? &g_ret.cstrs[(size_t)nargs] : nullptr;
  if (arg_descriptions)
    *arg_descriptions = nargs ? &g_ret.cstrs[2 * (size_t)nargs] : nullptr;
  if (return_type) *return_type = nullptr;
  return 0;
}

int MXImperativeInvoke(AtomicSymbolCreator creator, int num_inputs,
                       NDArrayHandle* inputs, int* num_outputs,
                       NDArrayHandle** outputs, int num_params,
                       const char** param_keys, const char** param_vals) {
  ensure_python();
  Gil gil;
  // in-place mode (reference semantics): caller-provided outputs are
  // written through and the caller keeps its handles
  bool inplace = (*num_outputs > 0 && *outputs != nullptr);
  PyObject* ins = make_handle_list((unsigned)num_inputs, inputs);
  PyObject* keys = make_str_list((unsigned)num_params, param_keys);
  PyObject* vals = make_str_list((unsigned)num_params, param_vals);
  PyObject* outs = inplace
      ? make_handle_list((unsigned)*num_outputs, *outputs)
      : (Py_INCREF(Py_None), Py_None);
  PyObject* args = Py_BuildValue(
      "(OOOOO)", reinterpret_cast<PyObject*>(creator), ins, keys, vals,
      outs);
  Py_DECREF(ins); Py_DECREF(keys); Py_DECREF(vals); Py_DECREF(outs);
  if (inplace) return simple("imperative_invoke", args);
  return out_handle_list("imperative_invoke", args, num_outputs, outputs);
}

int MXImperativeInvokeEx(AtomicSymbolCreator creator, int num_inputs,
                         NDArrayHandle* inputs, int* num_outputs,
                         NDArrayHandle** outputs, int num_params,
                         const char** param_keys, const char** param_vals,
                         const int** out_stypes) {
  int rc = MXImperativeInvoke(creator, num_inputs, inputs, num_outputs,
                              outputs, num_params, param_keys, param_vals);
  if (rc == 0 && out_stypes) {
    g_ret.ints.assign((size_t)*num_outputs, 0);  // kDefaultStorage
    *out_stypes = g_ret.ints.data();
  }
  return rc;
}

// -- autograd --------------------------------------------------------------

int MXAutogradSetIsRecording(int is_recording, int* prev) {
  Gil gil;
  long v = 0;
  PyObject* args = Py_BuildValue("(i)", is_recording);
  if (out_long("autograd_set_recording", args, &v) != 0) return -1;
  if (prev) *prev = (int)v;
  return 0;
}

int MXAutogradSetIsTraining(int is_training, int* prev) {
  Gil gil;
  long v = 0;
  PyObject* args = Py_BuildValue("(i)", is_training);
  if (out_long("autograd_set_training", args, &v) != 0) return -1;
  if (prev) *prev = (int)v;
  return 0;
}

int MXAutogradIsRecording(bool* curr) {
  Gil gil;
  long v = 0;
  if (out_long("autograd_is_recording", nullptr, &v) != 0) return -1;
  *curr = v != 0;
  return 0;
}

int MXAutogradIsTraining(bool* curr) {
  Gil gil;
  long v = 0;
  if (out_long("autograd_is_training", nullptr, &v) != 0) return -1;
  *curr = v != 0;
  return 0;
}

int MXAutogradMarkVariables(mx_uint num_var, NDArrayHandle* var_handles,
                            mx_uint* reqs_array,
                            NDArrayHandle* grad_handles) {
  Gil gil;
  PyObject* vars = make_handle_list(num_var, var_handles);
  PyObject* grads = make_handle_list(num_var, grad_handles);
  PyObject* reqs = make_uint_list(num_var, reqs_array);
  PyObject* args = Py_BuildValue("(OOO)", vars, reqs, grads);
  Py_DECREF(vars); Py_DECREF(grads); Py_DECREF(reqs);
  return simple("autograd_mark_variables", args);
}

int MXAutogradBackwardEx(mx_uint num_output, NDArrayHandle* output_handles,
                         NDArrayHandle* ograd_handles,
                         mx_uint num_variables,
                         NDArrayHandle* var_handles, int retain_graph,
                         int create_graph, int is_train,
                         NDArrayHandle** grad_handles, int** grad_stypes) {
  Gil gil;
  PyObject* outs = make_handle_list(num_output, output_handles);
  PyObject* ograds = ograd_handles
      ? make_handle_list(num_output, ograd_handles)
      : (Py_INCREF(Py_None), Py_None);
  if (num_variables != 0) {
    // explicit-variable form (reference: c_api_ndarray.cc:324 →
    // Imperative::Backward(variables)): returns grads for the named
    // vars without writing their .grad buffers
    PyObject* vars = make_handle_list(num_variables, var_handles);
    PyObject* args = Py_BuildValue("(OOOiii)", outs, ograds, vars,
                                   retain_graph, create_graph, is_train);
    Py_DECREF(outs); Py_DECREF(ograds); Py_DECREF(vars);
    int ngrads = 0;
    NDArrayHandle* sink = nullptr;
    int rc = out_handle_list("autograd_backward_ex", args, &ngrads,
                             grad_handles ? grad_handles : &sink);
    if (rc == 0 && grad_stypes) {
      g_ret.ints.assign((size_t)ngrads, 0);  // kDefaultStorage
      *grad_stypes = g_ret.ints.data();
    }
    return rc;
  }
  PyObject* args = Py_BuildValue("(OOiii)", outs, ograds, retain_graph,
                                 is_train, create_graph);
  Py_DECREF(outs); Py_DECREF(ograds);
  int rc = simple("autograd_backward", args);
  if (rc == 0 && grad_handles) *grad_handles = nullptr;
  if (rc == 0 && grad_stypes) *grad_stypes = nullptr;
  return rc;
}

int MXAutogradBackward(mx_uint num_output, NDArrayHandle* output_handles,
                       NDArrayHandle* ograd_handles, int retain_graph) {
  return MXAutogradBackwardEx(num_output, output_handles, ograd_handles, 0,
                              nullptr, retain_graph, 0, 1, nullptr,
                              nullptr);
}

int MXAutogradComputeGradient(mx_uint num_output,
                              NDArrayHandle* output_handles) {
  return MXAutogradBackward(num_output, output_handles, nullptr, 0);
}

// -- symbol manipulation ---------------------------------------------------

int MXSymbolCreateVariable(const char* name, SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", name);
  return out_handle("symbol_create_variable", args, out);
}

int MXSymbolCreateAtomicSymbol(AtomicSymbolCreator creator,
                               mx_uint num_param, const char** keys,
                               const char** vals, SymbolHandle* out) {
  Gil gil;
  PyObject* k = make_str_list(num_param, keys);
  PyObject* v = make_str_list(num_param, vals);
  PyObject* args = Py_BuildValue(
      "(OOO)", reinterpret_cast<PyObject*>(creator), k, v);
  Py_DECREF(k); Py_DECREF(v);
  return out_handle("symbol_create_atomic", args, out);
}

int MXSymbolCompose(SymbolHandle sym, const char* name, mx_uint num_args,
                    const char** keys, SymbolHandle* args_in) {
  Gil gil;
  PyObject* arr = make_handle_list(num_args, args_in);
  PyObject* k = keys ? make_str_list(num_args, keys)
                     : (Py_INCREF(Py_None), Py_None);
  PyObject* args = Py_BuildValue(
      "(OsOO)", reinterpret_cast<PyObject*>(sym), name ? name : "", arr,
      k);
  Py_DECREF(arr);
  Py_DECREF(k);
  return simple("symbol_compose", args);
}

int MXSymbolCopy(SymbolHandle sym, SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", reinterpret_cast<PyObject*>(sym));
  return out_handle("symbol_copy", args, out);
}

int MXSymbolPrint(SymbolHandle sym, const char** out_str) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", reinterpret_cast<PyObject*>(sym));
  return out_text("symbol_print", args, out_str);
}

int MXSymbolGetName(SymbolHandle sym, const char** out, int* success) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", reinterpret_cast<PyObject*>(sym));
  int rc = out_text("symbol_get_name", args, out);
  if (rc == 0) *success = (*out != nullptr);
  return rc;
}

int MXSymbolGetAttr(SymbolHandle sym, const char* key, const char** out,
                    int* success) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Os)", reinterpret_cast<PyObject*>(sym), key);
  int rc = out_text("symbol_get_attr", args, out);
  if (rc == 0) *success = (*out != nullptr);
  return rc;
}

int MXSymbolSetAttr(SymbolHandle sym, const char* key, const char* value) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Oss)", reinterpret_cast<PyObject*>(sym), key, value);
  return simple("symbol_set_attr", args);
}

static int list_attr_impl(SymbolHandle sym, int shallow, mx_uint* out_size,
                          const char*** out) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Oi)", reinterpret_cast<PyObject*>(sym), shallow);
  unsigned flat = 0;
  int rc = out_str_list("symbol_list_attr", args, &flat, out);
  if (rc == 0) *out_size = flat / 2;   // reference: k/v pair count
  return rc;
}

int MXSymbolListAttr(SymbolHandle sym, mx_uint* out_size,
                     const char*** out) {
  return list_attr_impl(sym, 0, out_size, out);
}

int MXSymbolListAttrShallow(SymbolHandle sym, mx_uint* out_size,
                            const char*** out) {
  return list_attr_impl(sym, 1, out_size, out);
}

int MXSymbolGetInternals(SymbolHandle sym, SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", reinterpret_cast<PyObject*>(sym));
  return out_handle("symbol_get_internals", args, out);
}

int MXSymbolGetOutput(SymbolHandle sym, mx_uint index, SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OI)", reinterpret_cast<PyObject*>(sym), index);
  return out_handle("symbol_get_output", args, out);
}

int MXSymbolGetNumOutputs(SymbolHandle sym, mx_uint* output_count) {
  Gil gil;
  long v = 0;
  PyObject* args = Py_BuildValue("(O)", reinterpret_cast<PyObject*>(sym));
  if (out_long("symbol_get_num_outputs", args, &v) != 0) return -1;
  *output_count = (mx_uint)v;
  return 0;
}

int MXSymbolCreateGroup(mx_uint num_symbols, SymbolHandle* symbols,
                        SymbolHandle* out) {
  Gil gil;
  PyObject* lst = make_handle_list(num_symbols, symbols);
  PyObject* args = Py_BuildValue("(O)", lst);
  Py_DECREF(lst);
  return out_handle("symbol_create_group", args, out);
}

int MXSymbolCreateFromFile(const char* fname, SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", fname);
  return out_handle("symbol_from_file", args, out);
}

int MXSymbolSaveToFile(SymbolHandle sym, const char* fname) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Os)", reinterpret_cast<PyObject*>(sym), fname);
  return simple("symbol_to_file", args);
}

static int infer_shape_impl(SymbolHandle sym, mx_uint num_args,
                            const char** keys, const mx_uint* arg_ind_ptr,
                            const mx_uint* arg_shape_data, int partial,
                            mx_uint* in_shape_size,
                            const mx_uint** in_shape_ndim,
                            const mx_uint*** in_shape_data,
                            mx_uint* out_shape_size,
                            const mx_uint** out_shape_ndim,
                            const mx_uint*** out_shape_data,
                            mx_uint* aux_shape_size,
                            const mx_uint** aux_shape_ndim,
                            const mx_uint*** aux_shape_data,
                            int* complete) {
  ensure_python();
  Gil gil;
  PyObject* k = make_str_list(num_args, keys);
  PyObject* ind = make_uint_list(num_args + 1, arg_ind_ptr);
  mx_uint total = num_args ? arg_ind_ptr[num_args] : 0;
  PyObject* data = make_uint_list(total, arg_shape_data);
  PyObject* args = Py_BuildValue(
      "(OOOOi)", reinterpret_cast<PyObject*>(sym), k, ind, data, partial);
  Py_DECREF(k); Py_DECREF(ind); Py_DECREF(data);
  PyObject* tup = call("symbol_infer_shape", args);
  Py_DECREF(args);
  if (!tup) { set_error_from_python(); return -1; }
  g_ret.sbufs.clear();
  mx_uint* sizes[3] = {in_shape_size, out_shape_size, aux_shape_size};
  const mx_uint** ndims[3] = {in_shape_ndim, out_shape_ndim,
                              aux_shape_ndim};
  const mx_uint*** datas[3] = {in_shape_data, out_shape_data,
                               aux_shape_data};
  // fill all buffers first (vector growth would invalidate pointers)
  for (int g = 0; g < 3; ++g) {
    PyObject* lst = PyTuple_GetItem(tup, g);
    Py_ssize_t n = PyList_Size(lst);
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* shp = PyList_GetItem(lst, i);
      std::vector<unsigned> dims;
      for (Py_ssize_t d = 0; d < PyList_Size(shp); ++d)
        dims.push_back((unsigned)PyLong_AsUnsignedLong(
            PyList_GetItem(shp, d)));
      g_ret.sbufs.push_back(std::move(dims));
    }
  }
  size_t cursor = 0;
  for (int g = 0; g < 3; ++g) {
    PyObject* lst = PyTuple_GetItem(tup, g);
    Py_ssize_t n = PyList_Size(lst);
    g_ret.ndims[g].clear();
    g_ret.sptrs[g].clear();
    for (Py_ssize_t i = 0; i < n; ++i, ++cursor) {
      g_ret.ndims[g].push_back((unsigned)g_ret.sbufs[cursor].size());
      g_ret.sptrs[g].push_back(g_ret.sbufs[cursor].data());
    }
    *sizes[g] = (mx_uint)n;
    *ndims[g] = g_ret.ndims[g].data();
    *datas[g] = g_ret.sptrs[g].data();
  }
  *complete = (int)PyLong_AsLong(PyTuple_GetItem(tup, 3));
  Py_DECREF(tup);
  return 0;
}

int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args,
                       const char** keys, const mx_uint* arg_ind_ptr,
                       const mx_uint* arg_shape_data,
                       mx_uint* in_shape_size,
                       const mx_uint** in_shape_ndim,
                       const mx_uint*** in_shape_data,
                       mx_uint* out_shape_size,
                       const mx_uint** out_shape_ndim,
                       const mx_uint*** out_shape_data,
                       mx_uint* aux_shape_size,
                       const mx_uint** aux_shape_ndim,
                       const mx_uint*** aux_shape_data, int* complete) {
  return infer_shape_impl(sym, num_args, keys, arg_ind_ptr, arg_shape_data,
                          0, in_shape_size, in_shape_ndim, in_shape_data,
                          out_shape_size, out_shape_ndim, out_shape_data,
                          aux_shape_size, aux_shape_ndim, aux_shape_data,
                          complete);
}

int MXSymbolInferShapePartial(SymbolHandle sym, mx_uint num_args,
                              const char** keys,
                              const mx_uint* arg_ind_ptr,
                              const mx_uint* arg_shape_data,
                              mx_uint* in_shape_size,
                              const mx_uint** in_shape_ndim,
                              const mx_uint*** in_shape_data,
                              mx_uint* out_shape_size,
                              const mx_uint** out_shape_ndim,
                              const mx_uint*** out_shape_data,
                              mx_uint* aux_shape_size,
                              const mx_uint** aux_shape_ndim,
                              const mx_uint*** aux_shape_data,
                              int* complete) {
  return infer_shape_impl(sym, num_args, keys, arg_ind_ptr, arg_shape_data,
                          1, in_shape_size, in_shape_ndim, in_shape_data,
                          out_shape_size, out_shape_ndim, out_shape_data,
                          aux_shape_size, aux_shape_ndim, aux_shape_data,
                          complete);
}

static int infer_type_impl(SymbolHandle sym, mx_uint num_args,
                           const char** keys, const int* arg_type_data,
                           int partial, mx_uint* in_size, const int** in,
                           mx_uint* out_size, const int** out,
                           mx_uint* aux_size, const int** aux,
                           int* complete) {
  ensure_python();
  Gil gil;
  PyObject* k = make_str_list(num_args, keys);
  PyObject* t = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i)
    PyList_SetItem(t, i, PyLong_FromLong(arg_type_data[i]));
  PyObject* args = Py_BuildValue(
      "(OOOi)", reinterpret_cast<PyObject*>(sym), k, t, partial);
  Py_DECREF(k); Py_DECREF(t);
  PyObject* tup = call("symbol_infer_type", args);
  Py_DECREF(args);
  if (!tup) { set_error_from_python(); return -1; }
  g_ret.ints.clear();
  mx_uint* sizes[3] = {in_size, out_size, aux_size};
  const int** outs[3] = {in, out, aux};
  std::vector<size_t> starts;
  for (int g = 0; g < 3; ++g) {
    PyObject* lst = PyTuple_GetItem(tup, g);
    starts.push_back(g_ret.ints.size());
    for (Py_ssize_t i = 0; i < PyList_Size(lst); ++i)
      g_ret.ints.push_back((int)PyLong_AsLong(PyList_GetItem(lst, i)));
  }
  for (int g = 0; g < 3; ++g) {
    PyObject* lst = PyTuple_GetItem(tup, g);
    *sizes[g] = (mx_uint)PyList_Size(lst);
    *outs[g] = g_ret.ints.data() + starts[g];
  }
  *complete = (int)PyLong_AsLong(PyTuple_GetItem(tup, 3));
  Py_DECREF(tup);
  return 0;
}

int MXSymbolInferType(SymbolHandle sym, mx_uint num_args, const char** keys,
                      const int* arg_type_data, mx_uint* in_type_size,
                      const int** in_type_data, mx_uint* out_type_size,
                      const int** out_type_data, mx_uint* aux_type_size,
                      const int** aux_type_data, int* complete) {
  return infer_type_impl(sym, num_args, keys, arg_type_data, 0,
                         in_type_size, in_type_data, out_type_size,
                         out_type_data, aux_type_size, aux_type_data,
                         complete);
}

int MXSymbolInferTypePartial(SymbolHandle sym, mx_uint num_args,
                             const char** keys, const int* arg_type_data,
                             mx_uint* in_type_size, const int** in_type_data,
                             mx_uint* out_type_size,
                             const int** out_type_data,
                             mx_uint* aux_type_size,
                             const int** aux_type_data, int* complete) {
  return infer_type_impl(sym, num_args, keys, arg_type_data, 1,
                         in_type_size, in_type_data, out_type_size,
                         out_type_data, aux_type_size, aux_type_data,
                         complete);
}

// -- executor --------------------------------------------------------------

int MXExecutorBind(SymbolHandle symbol_handle, int dev_type, int dev_id,
                   mx_uint len, NDArrayHandle* in_args,
                   NDArrayHandle* arg_grad_store, mx_uint* grad_req_type,
                   mx_uint aux_states_len, NDArrayHandle* aux_states,
                   ExecutorHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* args_l = make_handle_list(len, in_args);
  PyObject* grads_l = make_handle_list(len, arg_grad_store);
  PyObject* reqs_l = make_uint_list(len, grad_req_type);
  PyObject* aux_l = make_handle_list(aux_states_len, aux_states);
  PyObject* args = Py_BuildValue(
      "(OiiOOOO)", reinterpret_cast<PyObject*>(symbol_handle), dev_type,
      dev_id, args_l, grads_l, reqs_l, aux_l);
  Py_DECREF(args_l); Py_DECREF(grads_l); Py_DECREF(reqs_l);
  Py_DECREF(aux_l);
  return out_handle("executor_bind", args, out);
}

int MXExecutorBindX(SymbolHandle symbol_handle, int dev_type, int dev_id,
                    mx_uint /*num_map_keys*/, const char** /*map_keys*/,
                    const int* /*map_dev_types*/, const int* /*map_dev_ids*/,
                    mx_uint len, NDArrayHandle* in_args,
                    NDArrayHandle* arg_grad_store, mx_uint* grad_req_type,
                    mx_uint aux_states_len, NDArrayHandle* aux_states,
                    ExecutorHandle* out) {
  return MXExecutorBind(symbol_handle, dev_type, dev_id, len, in_args,
                        arg_grad_store, grad_req_type, aux_states_len,
                        aux_states, out);
}

int MXExecutorBindEX(SymbolHandle symbol_handle, int dev_type, int dev_id,
                     mx_uint num_map_keys, const char** map_keys,
                     const int* map_dev_types, const int* map_dev_ids,
                     mx_uint len, NDArrayHandle* in_args,
                     NDArrayHandle* arg_grad_store, mx_uint* grad_req_type,
                     mx_uint aux_states_len, NDArrayHandle* aux_states,
                     ExecutorHandle /*shared_exec*/, ExecutorHandle* out) {
  return MXExecutorBindX(symbol_handle, dev_type, dev_id, num_map_keys,
                         map_keys, map_dev_types, map_dev_ids, len, in_args,
                         arg_grad_store, grad_req_type, aux_states_len,
                         aux_states, out);
}

int MXExecutorForward(ExecutorHandle handle, int is_train) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Oi)", reinterpret_cast<PyObject*>(handle), is_train);
  return simple("executor_forward", args);
}

int MXExecutorBackward(ExecutorHandle handle, mx_uint len,
                       NDArrayHandle* head_grads) {
  Gil gil;
  PyObject* grads = make_handle_list(len, head_grads);
  PyObject* args = Py_BuildValue(
      "(OO)", reinterpret_cast<PyObject*>(handle), grads);
  Py_DECREF(grads);
  return simple("executor_backward", args);
}

int MXExecutorBackwardEx(ExecutorHandle handle, mx_uint len,
                         NDArrayHandle* head_grads, int /*is_train*/) {
  return MXExecutorBackward(handle, len, head_grads);
}

int MXExecutorOutputs(ExecutorHandle handle, mx_uint* out_size,
                      NDArrayHandle** out) {
  Gil gil;
  int n = 0;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  int rc = out_handle_list("executor_outputs", args, &n, out);
  if (rc == 0) *out_size = (mx_uint)n;
  return rc;
}

int MXExecutorPrint(ExecutorHandle handle, const char** out_str) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  return out_text("executor_print", args, out_str);
}

int MXExecutorFree(ExecutorHandle handle) { return MXNDArrayFree(handle); }

// -- cached op -------------------------------------------------------------

int MXCreateCachedOpEx(SymbolHandle handle, int num_flags,
                       const char** keys, const char** vals,
                       CachedOpHandle* out) {
  Gil gil;
  PyObject* k = make_str_list((unsigned)num_flags, keys);
  PyObject* v = make_str_list((unsigned)num_flags, vals);
  PyObject* args = Py_BuildValue(
      "(OOO)", reinterpret_cast<PyObject*>(handle), k, v);
  Py_DECREF(k); Py_DECREF(v);
  return out_handle("cached_op_create", args, out);
}

int MXCreateCachedOp(SymbolHandle handle, CachedOpHandle* out) {
  return MXCreateCachedOpEx(handle, 0, nullptr, nullptr, out);
}

int MXFreeCachedOp(CachedOpHandle handle) { return MXNDArrayFree(handle); }

int MXInvokeCachedOp(CachedOpHandle handle, int num_inputs,
                     NDArrayHandle* inputs, int* num_outputs,
                     NDArrayHandle** outputs) {
  Gil gil;
  PyObject* ins = make_handle_list((unsigned)num_inputs, inputs);
  PyObject* args = Py_BuildValue(
      "(OO)", reinterpret_cast<PyObject*>(handle), ins);
  Py_DECREF(ins);
  return out_handle_list("cached_op_invoke", args, num_outputs, outputs);
}

int MXInvokeCachedOpEx(CachedOpHandle handle, int num_inputs,
                       NDArrayHandle* inputs, int* num_outputs,
                       NDArrayHandle** outputs, const int** out_stypes) {
  int rc = MXInvokeCachedOp(handle, num_inputs, inputs, num_outputs,
                            outputs);
  if (rc == 0 && out_stypes) {
    g_ret.ints.assign((size_t)*num_outputs, 0);  // kDefaultStorage
    *out_stypes = g_ret.ints.data();
  }
  return rc;
}

// -- data iterators --------------------------------------------------------

int MXListDataIters(mx_uint* out_size, DataIterCreator** out_array) {
  Gil gil;
  PyObject* lst = creator_list("list_data_iters");
  if (!lst) { set_error_from_python(); return -1; }
  Py_ssize_t n = PyList_Size(lst);
  g_ret.handles.clear();
  for (Py_ssize_t i = 0; i < n; ++i)
    g_ret.handles.push_back(PyList_GetItem(lst, i));  // cache keeps alive
  *out_size = (mx_uint)n;
  *out_array = g_ret.handles.data();
  return 0;
}

int MXDataIterGetIterInfo(DataIterCreator creator, const char** name,
                          const char** description, mx_uint* num_args,
                          const char*** arg_names,
                          const char*** arg_type_infos,
                          const char*** arg_descriptions) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(creator));
  PyObject* tup = call("data_iter_info", args);
  Py_DECREF(args);
  if (!tup) { set_error_from_python(); return -1; }
  g_ret.strings.clear();
  g_ret.cstrs.clear();
  const char* n0 = PyUnicode_AsUTF8(PyTuple_GetItem(tup, 0));
  const char* d0 = PyUnicode_AsUTF8(PyTuple_GetItem(tup, 1));
  PyObject* anames = PyTuple_GetItem(tup, 2);
  PyObject* atypes = PyTuple_GetItem(tup, 3);
  PyObject* adescs = PyTuple_GetItem(tup, 4);
  Py_ssize_t nargs = anames ? PyList_Size(anames) : 0;
  g_ret.strings.reserve(2 + 3 * (size_t)nargs);
  g_ret.cstrs.reserve(3 * (size_t)nargs);
  g_ret.strings.emplace_back(n0 ? n0 : "");
  g_ret.strings.emplace_back(d0 ? d0 : "");
  for (int part = 0; part < 3; ++part) {
    PyObject* lst = part == 0 ? anames : (part == 1 ? atypes : adescs);
    for (Py_ssize_t i = 0; i < nargs; ++i) {
      const char* s = PyUnicode_AsUTF8(PyList_GetItem(lst, i));
      g_ret.strings.emplace_back(s ? s : "");
      g_ret.cstrs.push_back(g_ret.strings.back().c_str());
    }
  }
  Py_DECREF(tup);
  *name = g_ret.strings[0].c_str();
  *description = g_ret.strings[1].c_str();
  *num_args = (mx_uint)nargs;
  if (arg_names)
    *arg_names = nargs ? &g_ret.cstrs[0] : nullptr;
  if (arg_type_infos)
    *arg_type_infos = nargs ? &g_ret.cstrs[(size_t)nargs] : nullptr;
  if (arg_descriptions)
    *arg_descriptions = nargs ? &g_ret.cstrs[2 * (size_t)nargs] : nullptr;
  return 0;
}

int MXDataIterCreateIter(DataIterCreator creator, mx_uint num_param,
                         const char** keys, const char** vals,
                         DataIterHandle* out) {
  Gil gil;
  PyObject* k = make_str_list(num_param, keys);
  PyObject* v = make_str_list(num_param, vals);
  PyObject* args = Py_BuildValue(
      "(OOO)", reinterpret_cast<PyObject*>(creator), k, v);
  Py_DECREF(k); Py_DECREF(v);
  return out_handle("data_iter_create", args, out);
}

int MXDataIterFree(DataIterHandle handle) { return MXNDArrayFree(handle); }

int MXDataIterNext(DataIterHandle handle, int* out) {
  Gil gil;
  long v = 0;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  if (out_long("data_iter_next", args, &v) != 0) return -1;
  *out = (int)v;
  return 0;
}

int MXDataIterBeforeFirst(DataIterHandle handle) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  return simple("data_iter_before_first", args);
}

int MXDataIterGetData(DataIterHandle handle, NDArrayHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  return out_handle("data_iter_data", args, out);
}

int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  return out_handle("data_iter_label", args, out);
}

int MXDataIterGetPadNum(DataIterHandle handle, int* pad) {
  Gil gil;
  long v = 0;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  if (out_long("data_iter_pad", args, &v) != 0) return -1;
  *pad = (int)v;
  return 0;
}

int MXDataIterGetIndex(DataIterHandle handle,
                       unsigned long long** out_index,
                       unsigned long long* out_size) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  PyObject* lst = call("data_iter_index", args);
  Py_DECREF(args);
  if (!lst) { set_error_from_python(); return -1; }
  g_ret.idx64.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(lst); ++i)
    g_ret.idx64.push_back(PyLong_AsUnsignedLongLong(
        PyList_GetItem(lst, i)));
  Py_DECREF(lst);
  *out_size = g_ret.idx64.size();
  *out_index = g_ret.idx64.data();
  return 0;
}

// -- kvstore metadata ------------------------------------------------------

int MXKVStoreGetType(KVStoreHandle handle, const char** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  return out_text("kvstore_type", args, out);
}

int MXKVStoreGetRank(KVStoreHandle handle, int* out) {
  Gil gil;
  long v = 0;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  if (out_long("kvstore_rank", args, &v) != 0) return -1;
  *out = (int)v;
  return 0;
}

int MXKVStoreGetGroupSize(KVStoreHandle handle, int* out) {
  Gil gil;
  long v = 0;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  if (out_long("kvstore_group_size", args, &v) != 0) return -1;
  *out = (int)v;
  return 0;
}

int MXKVStoreBarrier(KVStoreHandle handle) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  return simple("kvstore_barrier", args);
}

int MXKVStoreIsWorkerNode(int* ret) { *ret = 1; return 0; }
int MXKVStoreIsServerNode(int* ret) { *ret = 0; return 0; }
int MXKVStoreIsSchedulerNode(int* ret) { *ret = 0; return 0; }
int MXKVStoreGetNumDeadNode(KVStoreHandle, const int, int* number_of_dead,
                            const int) { *number_of_dead = 0; return 0; }
int MXKVStoreSetBarrierBeforeExit(KVStoreHandle, const int) { return 0; }
int MXInitPSEnv(mx_uint, const char**, const char**) { return 0; }
int MXKVStoreRunServer(KVStoreHandle, void*, void*) {
  // no parameter-server role in the collective design (DIVERGENCES.md);
  // returning success lets reference launch shells exit cleanly
  return 0;
}
int MXKVStoreSendCommmandToServers(KVStoreHandle, int, const char*) {
  return 0;
}

static int kv_str_op(const char* fn, KVStoreHandle handle, mx_uint num,
                     const char** keys, NDArrayHandle* vals) {
  Gil gil;
  PyObject* pykeys = make_str_list(num, keys);
  PyObject* pyvals = make_handle_list(num, vals);
  PyObject* args = Py_BuildValue(
      "(OOO)", reinterpret_cast<PyObject*>(handle), pykeys, pyvals);
  Py_DECREF(pykeys); Py_DECREF(pyvals);
  return simple(fn, args);
}

int MXKVStoreInitEx(KVStoreHandle handle, mx_uint num, const char** keys,
                    NDArrayHandle* vals) {
  return kv_str_op("kvstore_init_str", handle, num, keys, vals);
}

int MXKVStorePushEx(KVStoreHandle handle, mx_uint num, const char** keys,
                    NDArrayHandle* vals, int /*priority*/) {
  return kv_str_op("kvstore_push_str", handle, num, keys, vals);
}

int MXKVStorePullEx(KVStoreHandle handle, mx_uint num, const char** keys,
                    NDArrayHandle* vals, int /*priority*/) {
  return kv_str_op("kvstore_pull_str", handle, num, keys, vals);
}

int MXKVStoreSetGradientCompression(KVStoreHandle handle, mx_uint num_params,
                                    const char** keys, const char** vals) {
  Gil gil;
  PyObject* k = make_str_list(num_params, keys);
  PyObject* v = make_str_list(num_params, vals);
  PyObject* args = Py_BuildValue(
      "(OOO)", reinterpret_cast<PyObject*>(handle), k, v);
  Py_DECREF(k); Py_DECREF(v);
  return simple("kvstore_set_gradient_compression", args);
}

// -- recordio --------------------------------------------------------------

int MXRecordIOWriterCreate(const char* uri, RecordIOHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", uri);
  return out_handle("recordio_writer_create", args, out);
}

int MXRecordIOReaderCreate(const char* uri, RecordIOHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", uri);
  return out_handle("recordio_reader_create", args, out);
}

static int recordio_free(RecordIOHandle handle) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  int rc = simple("recordio_close", args);
  MXNDArrayFree(handle);
  return rc;
}

int MXRecordIOWriterFree(RecordIOHandle handle) {
  return recordio_free(handle);
}

int MXRecordIOReaderFree(RecordIOHandle handle) {
  return recordio_free(handle);
}

int MXRecordIOWriterWriteRecord(RecordIOHandle handle, const char* buf,
                                size_t size) {
  Gil gil;
  PyObject* b = PyBytes_FromStringAndSize(buf, (Py_ssize_t)size);
  PyObject* args = Py_BuildValue(
      "(OO)", reinterpret_cast<PyObject*>(handle), b);
  Py_DECREF(b);
  return simple("recordio_write", args);
}

int MXRecordIOReaderReadRecord(RecordIOHandle handle, char const** buf,
                               size_t* size) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  PyObject* r = call("recordio_read", args);
  Py_DECREF(args);
  if (!r) { set_error_from_python(); return -1; }
  if (r == Py_None) {
    *buf = nullptr;
    *size = 0;
  } else {
    char* data = nullptr;
    Py_ssize_t n = 0;
    if (PyBytes_AsStringAndSize(r, &data, &n) != 0) {
      PyErr_Clear();
      Py_DECREF(r);
      g_last_error = "recordio read returned non-bytes";
      return -1;
    }
    g_ret.text.assign(data, n);
    *buf = g_ret.text.data();
    *size = (size_t)n;
  }
  Py_DECREF(r);
  return 0;
}

static int recordio_tell_impl(RecordIOHandle handle, size_t* pos) {
  Gil gil;
  long v = 0;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  if (out_long("recordio_tell", args, &v) != 0) return -1;
  *pos = (size_t)v;
  return 0;
}

int MXRecordIOWriterTell(RecordIOHandle handle, size_t* pos) {
  return recordio_tell_impl(handle, pos);
}

int MXRecordIOReaderTell(RecordIOHandle handle, size_t* pos) {
  return recordio_tell_impl(handle, pos);
}

int MXRecordIOReaderSeek(RecordIOHandle handle, size_t pos) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(On)", reinterpret_cast<PyObject*>(handle), (Py_ssize_t)pos);
  return simple("recordio_seek", args);
}

// -- profiler objects ------------------------------------------------------

int MXSetProfilerConfig(int num_params, const char* const* keys,
                        const char* const* vals) {
  Gil gil;
  PyObject* k = make_str_list((unsigned)num_params, keys);
  PyObject* v = make_str_list((unsigned)num_params, vals);
  PyObject* args = Py_BuildValue("(OO)", k, v);
  Py_DECREF(k); Py_DECREF(v);
  return simple("profiler_set_config", args);
}

int MXDumpProfile(int finished) {
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", finished);
  return simple("profiler_dump", args);
}

int MXDumpProcessProfile(int finished, int /*profile_process*/,
                         KVStoreHandle /*kv*/) {
  return MXDumpProfile(finished);
}

int MXProfilePause(int paused) {
  return simple(paused ? "profiler_pause" : "profiler_resume", nullptr);
}

int MXProcessProfilePause(int paused, int /*profile_process*/,
                          KVStoreHandle /*kv*/) {
  return MXProfilePause(paused);
}

int MXSetProcessProfilerState(int state, int /*profile_process*/,
                              KVStoreHandle /*kv*/) {
  return MXSetProfilerState(state);
}

int MXSetProcessProfilerConfig(int num_params, const char* const* keys,
                               const char* const* vals,
                               KVStoreHandle /*kv*/) {
  return MXSetProfilerConfig(num_params, keys, vals);
}

int MXProfileCreateDomain(const char* domain, ProfileHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", domain);
  return out_handle("profile_create_domain", args, out);
}

int MXProfileCreateTask(ProfileHandle domain, const char* name,
                        ProfileHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Os)", reinterpret_cast<PyObject*>(domain), name);
  return out_handle("profile_create_task", args, out);
}

int MXProfileCreateFrame(ProfileHandle domain, const char* name,
                         ProfileHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Os)", reinterpret_cast<PyObject*>(domain), name);
  return out_handle("profile_create_frame", args, out);
}

int MXProfileCreateEvent(const char* name, ProfileHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", name);
  return out_handle("profile_create_event", args, out);
}

int MXProfileCreateCounter(ProfileHandle domain, const char* name,
                           ProfileHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Os)", reinterpret_cast<PyObject*>(domain), name);
  return out_handle("profile_create_counter", args, out);
}

int MXProfileDestroyHandle(ProfileHandle handle) {
  return MXNDArrayFree(handle);
}

int MXProfileDurationStart(ProfileHandle handle) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  return simple("profile_duration_start", args);
}

int MXProfileDurationStop(ProfileHandle handle) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  return simple("profile_duration_stop", args);
}

int MXProfileSetCounter(ProfileHandle handle, unsigned long long value) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OK)", reinterpret_cast<PyObject*>(handle), value);
  return simple("profile_set_counter", args);
}

int MXProfileAdjustCounter(ProfileHandle handle, long long delta) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OL)", reinterpret_cast<PyObject*>(handle), delta);
  return simple("profile_adjust_counter", args);
}

int MXProfileSetMarker(ProfileHandle domain, const char* name,
                       const char* scope_kind) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Oss)", reinterpret_cast<PyObject*>(domain), name,
      scope_kind ? scope_kind : "process");
  return simple("profile_set_marker", args);
}

// -- misc runtime ----------------------------------------------------------

int MXNotifyShutdown() { return 0; }

int MXRandomSeed(int seed) {
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", seed);
  return simple("random_seed", args);
}

int MXRandomSeedContext(int seed, int /*dev_type*/, int /*dev_id*/) {
  return MXRandomSeed(seed);
}

int MXGetGPUCount(int* out) {
  Gil gil;
  long v = 0;
  if (out_long("num_gpus", nullptr, &v) != 0) return -1;
  *out = (int)v;
  return 0;
}

int MXGetGPUMemoryInformation64(int /*dev*/, unsigned long long* free_mem,
                                unsigned long long* total_mem) {
  // XLA owns device memory; report unknown-but-valid (reference returns
  // cudaMemGetInfo — no analog through PJRT here)
  *free_mem = 0;
  *total_mem = 0;
  return 0;
}

int MXGetGPUMemoryInformation(int dev, int* free_mem, int* total_mem) {
  unsigned long long f = 0, t = 0;
  int rc = MXGetGPUMemoryInformation64(dev, &f, &t);
  *free_mem = (int)f;
  *total_mem = (int)t;
  return rc;
}

int MXSetNumOMPThreads(int /*thread_num*/) { return 0; }
int MXEngineSetBulkSize(int /*bulk_size*/, int* prev_bulk_size) {
  if (prev_bulk_size) *prev_bulk_size = 15;
  return 0;
}

int MXIsNumpyCompatible(bool* curr) { *curr = false; return 0; }
int MXSetIsNumpyCompatible(int /*is_np_comp*/, int* prev) {
  if (prev) *prev = 0;
  return 0;
}

int MXLibInfoFeatures(const struct LibFeature** lib_features, size_t* size) {
  // the struct layout is reference-internal; expose the count with a
  // null table (callers wanting names use the Python runtime API)
  *lib_features = nullptr;
  *size = 0;
  return 0;
}

int MXListFunctions(mx_uint* out_size, void*** out_array) {
  // legacy NDArrayFunction registry: empty on this backend (ops live in
  // the imperative-invoke registry, MXListAllOpNames)
  g_ret.handles.clear();
  *out_size = 0;
  *out_array = g_ret.handles.data();
  return 0;
}

int MXGetFunction(const char* /*name*/, void** out) {
  *out = nullptr;
  g_last_error = "legacy NDArrayFunction registry is empty; use "
                 "MXImperativeInvoke";
  return -1;
}

}  // extern "C"

// ===========================================================================
// Round-4 second wave: SimpleBind/Reshape executors, symbol structure,
// two-phase quantization, sparse aux, shared memory, engine push
// ===========================================================================

extern "C" {

static int fill_handle_lists(PyObject* tup, mx_uint* num_in_args,
                             NDArrayHandle** in_args,
                             NDArrayHandle** arg_grads,
                             mx_uint* num_aux, NDArrayHandle** aux_states,
                             ExecutorHandle* out) {
  // tup = (executor, [args], [grads-with-None], [aux])
  PyObject* ex = PyTuple_GetItem(tup, 0);
  Py_INCREF(ex);
  *out = ex;
  PyObject* lists[3] = {PyTuple_GetItem(tup, 1), PyTuple_GetItem(tup, 2),
                        PyTuple_GetItem(tup, 3)};
  std::vector<void*>* stores[3] = {&g_ret.handles, &g_ret.handles2,
                                   &g_ret.handles3};
  for (int g = 0; g < 3; ++g) {
    stores[g]->clear();
    for (Py_ssize_t i = 0; i < PyList_Size(lists[g]); ++i) {
      PyObject* o = PyList_GetItem(lists[g], i);
      if (o == Py_None) {
        stores[g]->push_back(nullptr);
      } else {
        Py_INCREF(o);
        stores[g]->push_back(o);
      }
    }
  }
  *num_in_args = (mx_uint)g_ret.handles.size();
  *in_args = g_ret.handles.data();
  if (arg_grads) *arg_grads = g_ret.handles2.data();
  *num_aux = (mx_uint)g_ret.handles3.size();
  *aux_states = g_ret.handles3.data();
  return 0;
}

static int simple_bind_impl(SymbolHandle symbol_handle, int dev_type,
                            int dev_id, mx_uint num_req,
                            const char** req_names, const char** req_types,
                            mx_uint num_shapes, const char** shape_names,
                            const void* shape_data, int shape_data_is_int,
                            const mx_uint* shape_idx, mx_uint num_dtypes,
                            const char** dtype_names, const int* dtypes,
                            mx_uint* num_in_args, NDArrayHandle** in_args,
                            NDArrayHandle** arg_grads, mx_uint* num_aux,
                            NDArrayHandle** aux_states,
                            ExecutorHandle* out) {
  ensure_python();
  Gil gil;
  // names==NULL means positional (or uniform single-entry) semantics
  PyObject* rn = req_names ? make_str_list(num_req, req_names)
                           : (Py_INCREF(Py_None), Py_None);
  PyObject* rt = make_str_list(num_req, req_types);
  PyObject* sn = make_str_list(num_shapes, shape_names);
  mx_uint total = num_shapes ? shape_idx[num_shapes] : 0;
  PyObject* sd = PyList_New(total);
  for (mx_uint i = 0; i < total; ++i) {
    long v = shape_data_is_int
        ? (long)((const int*)shape_data)[i]
        : (long)((const mx_uint*)shape_data)[i];
    PyList_SetItem(sd, i, PyLong_FromLong(v));
  }
  PyObject* si = make_uint_list(num_shapes + 1, shape_idx);
  PyObject* dn = make_str_list(num_dtypes, dtype_names);
  PyObject* dc = PyList_New(num_dtypes);
  for (mx_uint i = 0; i < num_dtypes; ++i)
    PyList_SetItem(dc, i, PyLong_FromLong(dtypes ? dtypes[i] : 0));
  PyObject* args = Py_BuildValue(
      "(OiiOOOOOOO)", reinterpret_cast<PyObject*>(symbol_handle), dev_type,
      dev_id, rn, rt, sn, si, sd, dn, dc);
  Py_DECREF(rn); Py_DECREF(rt); Py_DECREF(sn); Py_DECREF(si);
  Py_DECREF(sd); Py_DECREF(dn); Py_DECREF(dc);
  PyObject* tup = call("executor_simple_bind", args);
  Py_DECREF(args);
  if (!tup) { set_error_from_python(); return -1; }
  int rc = fill_handle_lists(tup, num_in_args, in_args, arg_grads,
                             num_aux, aux_states, out);
  Py_DECREF(tup);
  return rc;
}

int MXExecutorSimpleBind(
    SymbolHandle symbol_handle, int dev_type, int dev_id,
    const mx_uint /*num_g2c_keys*/, const char** /*g2c_keys*/,
    const int* /*g2c_dev_types*/, const int* /*g2c_dev_ids*/,
    const mx_uint provided_grad_req_list_len,
    const char** provided_grad_req_names,
    const char** provided_grad_req_types,
    const mx_uint num_provided_arg_shapes,
    const char** provided_arg_shape_names,
    const mx_uint* provided_arg_shape_data,
    const mx_uint* provided_arg_shape_idx,
    const mx_uint num_provided_arg_dtypes,
    const char** provided_arg_dtype_names, const int* provided_arg_dtypes,
    const mx_uint /*num_provided_arg_stypes*/,
    const char** /*provided_arg_stype_names*/,
    const int* /*provided_arg_stypes*/,
    const mx_uint /*num_shared_arg_names*/,
    const char** /*shared_arg_name_list*/, int* shared_buffer_len,
    const char** /*shared_buffer_name_list*/,
    NDArrayHandle* /*shared_buffer_handle_list*/,
    const char*** updated_shared_buffer_name_list,
    NDArrayHandle** updated_shared_buffer_handle_list,
    mx_uint* num_in_args, NDArrayHandle** in_args,
    NDArrayHandle** arg_grads, mx_uint* num_aux_states,
    NDArrayHandle** aux_states, ExecutorHandle /*shared_exec_handle*/,
    ExecutorHandle* out) {
  // shared buffers / group2ctx / stypes have no analog here (XLA owns
  // memory and placement); report the shared buffer as unused
  if (shared_buffer_len) *shared_buffer_len = -1;
  if (updated_shared_buffer_name_list)
    *updated_shared_buffer_name_list = nullptr;
  if (updated_shared_buffer_handle_list)
    *updated_shared_buffer_handle_list = nullptr;
  return simple_bind_impl(
      symbol_handle, dev_type, dev_id, provided_grad_req_list_len,
      provided_grad_req_names, provided_grad_req_types,
      num_provided_arg_shapes, provided_arg_shape_names,
      provided_arg_shape_data, /*is_int=*/0, provided_arg_shape_idx,
      num_provided_arg_dtypes, provided_arg_dtype_names,
      provided_arg_dtypes, num_in_args, in_args, arg_grads,
      num_aux_states, aux_states, out);
}

int MXExecutorSimpleBindEx(
    SymbolHandle symbol_handle, int dev_type, int dev_id,
    const mx_uint num_g2c_keys, const char** g2c_keys,
    const int* g2c_dev_types, const int* g2c_dev_ids,
    const mx_uint provided_grad_req_list_len,
    const char** provided_grad_req_names,
    const char** provided_grad_req_types,
    const mx_uint num_provided_arg_shapes,
    const char** provided_arg_shape_names,
    const int* provided_arg_shape_data,
    const mx_uint* provided_arg_shape_idx,
    const mx_uint num_provided_arg_dtypes,
    const char** provided_arg_dtype_names, const int* provided_arg_dtypes,
    const mx_uint num_provided_arg_stypes,
    const char** provided_arg_stype_names, const int* provided_arg_stypes,
    const mx_uint num_shared_arg_names, const char** shared_arg_name_list,
    int* shared_buffer_len, const char** shared_buffer_name_list,
    NDArrayHandle* shared_buffer_handle_list,
    const char*** updated_shared_buffer_name_list,
    NDArrayHandle** updated_shared_buffer_handle_list,
    mx_uint* num_in_args, NDArrayHandle** in_args,
    NDArrayHandle** arg_grads, mx_uint* num_aux_states,
    NDArrayHandle** aux_states, ExecutorHandle shared_exec_handle,
    ExecutorHandle* out) {
  (void)num_g2c_keys; (void)g2c_keys; (void)g2c_dev_types;
  (void)g2c_dev_ids; (void)num_provided_arg_stypes;
  (void)provided_arg_stype_names; (void)provided_arg_stypes;
  (void)num_shared_arg_names; (void)shared_arg_name_list;
  (void)shared_buffer_name_list; (void)shared_buffer_handle_list;
  (void)shared_exec_handle;
  if (shared_buffer_len) *shared_buffer_len = -1;
  if (updated_shared_buffer_name_list)
    *updated_shared_buffer_name_list = nullptr;
  if (updated_shared_buffer_handle_list)
    *updated_shared_buffer_handle_list = nullptr;
  return simple_bind_impl(
      symbol_handle, dev_type, dev_id, provided_grad_req_list_len,
      provided_grad_req_names, provided_grad_req_types,
      num_provided_arg_shapes, provided_arg_shape_names,
      provided_arg_shape_data, /*is_int=*/1, provided_arg_shape_idx,
      num_provided_arg_dtypes, provided_arg_dtype_names,
      provided_arg_dtypes, num_in_args, in_args, arg_grads,
      num_aux_states, aux_states, out);
}

static int reshape_impl(int partial_shaping, int allow_up_sizing,
                        mx_uint num_shapes, const char** names,
                        const void* data, int data_is_int,
                        const mx_uint* idx, mx_uint* num_in_args,
                        NDArrayHandle** in_args, NDArrayHandle** arg_grads,
                        mx_uint* num_aux, NDArrayHandle** aux_states,
                        ExecutorHandle shared_exec, ExecutorHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* sn = make_str_list(num_shapes, names);
  mx_uint total = num_shapes ? idx[num_shapes] : 0;
  PyObject* sd = PyList_New(total);
  for (mx_uint i = 0; i < total; ++i) {
    long v = data_is_int ? (long)((const int*)data)[i]
                         : (long)((const mx_uint*)data)[i];
    PyList_SetItem(sd, i, PyLong_FromLong(v));
  }
  PyObject* si = make_uint_list(num_shapes + 1, idx);
  PyObject* args = Py_BuildValue(
      "(OiiOOO)", reinterpret_cast<PyObject*>(shared_exec),
      partial_shaping, allow_up_sizing, sn, si, sd);
  Py_DECREF(sn); Py_DECREF(si); Py_DECREF(sd);
  PyObject* tup = call("executor_reshape", args);
  Py_DECREF(args);
  if (!tup) { set_error_from_python(); return -1; }
  int rc = fill_handle_lists(tup, num_in_args, in_args, arg_grads,
                             num_aux, aux_states, out);
  Py_DECREF(tup);
  return rc;
}

int MXExecutorReshape(int partial_shaping, int allow_up_sizing,
                      int /*dev_type*/, int /*dev_id*/,
                      mx_uint /*num_map_keys*/, const char** /*map_keys*/,
                      const int* /*map_dev_types*/,
                      const int* /*map_dev_ids*/, mx_uint num_provided,
                      const char** provided_names,
                      const mx_uint* provided_data,
                      const mx_uint* provided_idx, mx_uint* num_in_args,
                      NDArrayHandle** in_args, NDArrayHandle** arg_grads,
                      mx_uint* num_aux_states, NDArrayHandle** aux_states,
                      ExecutorHandle shared_exec, ExecutorHandle* out) {
  return reshape_impl(partial_shaping, allow_up_sizing, num_provided,
                      provided_names, provided_data, /*is_int=*/0,
                      provided_idx, num_in_args, in_args, arg_grads,
                      num_aux_states, aux_states, shared_exec, out);
}

int MXExecutorReshapeEx(int partial_shaping, int allow_up_sizing,
                        int /*dev_type*/, int /*dev_id*/,
                        mx_uint /*num_map_keys*/, const char** /*map_keys*/,
                        const int* /*map_dev_types*/,
                        const int* /*map_dev_ids*/, mx_uint num_provided,
                        const char** provided_names,
                        const int* provided_data,
                        const mx_uint* provided_idx, mx_uint* num_in_args,
                        NDArrayHandle** in_args, NDArrayHandle** arg_grads,
                        mx_uint* num_aux_states, NDArrayHandle** aux_states,
                        ExecutorHandle shared_exec, ExecutorHandle* out) {
  return reshape_impl(partial_shaping, allow_up_sizing, num_provided,
                      provided_names, provided_data, /*is_int=*/1,
                      provided_idx, num_in_args, in_args, arg_grads,
                      num_aux_states, aux_states, shared_exec, out);
}

int MXExecutorGetOptimizedSymbol(ExecutorHandle handle,
                                 SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  return out_handle("executor_optimized_symbol", args, out);
}

// -- symbol structure ------------------------------------------------------

int MXSymbolGetChildren(SymbolHandle sym, SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", reinterpret_cast<PyObject*>(sym));
  return out_handle("symbol_get_children", args, out);
}

int MXSymbolGetInputSymbols(SymbolHandle sym, SymbolHandle** inputs,
                            int* input_size) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", reinterpret_cast<PyObject*>(sym));
  return out_handle_list("symbol_get_inputs", args, input_size,
                         reinterpret_cast<void***>(inputs));
}

int MXSymbolGrad(SymbolHandle /*sym*/, mx_uint /*num_wrt*/,
                 const char** /*wrt*/, SymbolHandle* /*out*/) {
  // reference parity: MXSymbolGrad is deprecated and fails there too
  Gil gil;
  PyObject* r = call("symbol_grad_unsupported", nullptr);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return -1;
}

int MXGenBackendSubgraph(SymbolHandle sym, const char* backend,
                         SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Os)", reinterpret_cast<PyObject*>(sym), backend);
  return out_handle("gen_backend_subgraph", args, out);
}

// -- quantization ----------------------------------------------------------

int MXQuantizeSymbol(SymbolHandle sym_handle, SymbolHandle* ret_sym_handle,
                     const mx_uint num_excluded, const char** excluded,
                     const mx_uint /*num_offline*/,
                     const char** /*offline_params*/,
                     const char* /*quantized_dtype*/,
                     const bool /*calib_quantize*/) {
  Gil gil;
  PyObject* ex = make_str_list(num_excluded, excluded);
  PyObject* args = Py_BuildValue(
      "(OO)", reinterpret_cast<PyObject*>(sym_handle), ex);
  Py_DECREF(ex);
  return out_handle("quantize_symbol", args, ret_sym_handle);
}

int MXSetCalibTableToQuantizedSymbol(SymbolHandle qsym_handle,
                                     const mx_uint num_layers,
                                     const char** layer_names,
                                     const float* low_quantiles,
                                     const float* high_quantiles,
                                     SymbolHandle* ret_sym_handle) {
  Gil gil;
  PyObject* names = make_str_list(num_layers, layer_names);
  PyObject* lows = PyList_New(num_layers);
  PyObject* highs = PyList_New(num_layers);
  for (mx_uint i = 0; i < num_layers; ++i) {
    PyList_SetItem(lows, i, PyFloat_FromDouble(low_quantiles[i]));
    PyList_SetItem(highs, i, PyFloat_FromDouble(high_quantiles[i]));
  }
  PyObject* args = Py_BuildValue(
      "(OOOO)", reinterpret_cast<PyObject*>(qsym_handle), names, lows,
      highs);
  Py_DECREF(names); Py_DECREF(lows); Py_DECREF(highs);
  return out_handle("set_calib_table", args, ret_sym_handle);
}

// -- sparse facade aux -----------------------------------------------------

int MXNDArrayCreateSparseEx(int storage_type, const mx_uint* shape,
                            mx_uint ndim, int dev_type, int dev_id,
                            int /*delay_alloc*/, int dtype,
                            mx_uint /*num_aux*/, int* /*aux_type*/,
                            mx_uint* /*aux_ndims*/,
                            const mx_uint* /*aux_shape*/,
                            NDArrayHandle* out) {
  Gil gil;
  PyObject* pyshape = make_uint_list(ndim, shape);
  PyObject* args = Py_BuildValue("(iOiii)", storage_type, pyshape,
                                 dev_type, dev_id, dtype);
  Py_DECREF(pyshape);
  return out_handle("ndarray_create_sparse", args, out);
}

int MXNDArrayGetAuxType(NDArrayHandle handle, mx_uint i, int* out_type) {
  Gil gil;
  long v = 0;
  PyObject* args = Py_BuildValue(
      "(OI)", reinterpret_cast<PyObject*>(handle), i);
  if (out_long("ndarray_aux_type", args, &v) != 0) return -1;
  *out_type = (int)v;
  return 0;
}

int MXNDArrayGetAuxNDArray(NDArrayHandle handle, mx_uint i,
                           NDArrayHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OI)", reinterpret_cast<PyObject*>(handle), i);
  return out_handle("ndarray_get_aux", args, out);
}

int MXNDArrayGetDataNDArray(NDArrayHandle handle, NDArrayHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  return out_handle("ndarray_detach", args, out);
}

// -- shared memory ---------------------------------------------------------

// POSIX shm segments are named, not (pid, id) pairs: names are
// interned in a process-lifetime table, the index is the id, and the
// pid slot carries a scheme marker. Cross-process callers exchange the
// NAME via MXNDArraySharedMemName (an extension entry point below).
static std::vector<std::string>& shm_names() {
  static std::vector<std::string>* names = new std::vector<std::string>();
  return *names;
}

int MXNDArrayGetSharedMemHandle(NDArrayHandle handle, int* shared_pid,
                                int* shared_id) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  PyObject* tup = call("ndarray_to_shared_mem", args);
  Py_DECREF(args);
  if (!tup) { set_error_from_python(); return -1; }
  const char* nm = PyUnicode_AsUTF8(PyTuple_GetItem(tup, 0));
  shm_names().push_back(nm ? nm : "");
  Py_DECREF(tup);
  *shared_pid = 0;
  *shared_id = (int)shm_names().size() - 1;
  return 0;
}

int MXNDArraySharedMemName(int shared_id, const char** out_name) {
  // extension: the POSIX name for cross-process exchange
  if (shared_id < 0 || (size_t)shared_id >= shm_names().size()) {
    g_last_error = "unknown shared-mem id";
    return -1;
  }
  g_ret.text = shm_names()[shared_id];
  *out_name = g_ret.text.c_str();
  return 0;
}

int MXNDArrayCreateFromSharedMem(int shared_pid, int shared_id,
                                 const mx_uint* shape, mx_uint ndim,
                                 int dtype, NDArrayHandle* out) {
  ensure_python();
  Gil gil;
  (void)shared_pid;
  if (shared_id < 0 || (size_t)shared_id >= shm_names().size()) {
    g_last_error = "unknown shared-mem id (cross-process callers attach "
                   "by name via MXNDArraySharedMemName)";
    return -1;
  }
  PyObject* pyshape = make_uint_list(ndim, shape);
  PyObject* args = Py_BuildValue(
      "(sOi)", shm_names()[shared_id].c_str(), pyshape, dtype);
  Py_DECREF(pyshape);
  return out_handle("ndarray_from_shared_mem", args, out);
}

int MXNDArrayCreateFromSharedMemEx(int shared_pid, int shared_id,
                                   const int* shape, int ndim, int dtype,
                                   NDArrayHandle* out) {
  std::vector<mx_uint> u(shape, shape + ndim);
  return MXNDArrayCreateFromSharedMem(shared_pid, shared_id, u.data(),
                                      (mx_uint)ndim, dtype, out);
}

// -- kvstore sparse pulls --------------------------------------------------

int MXKVStorePullRowSparse(KVStoreHandle handle, mx_uint num,
                           const int* keys, NDArrayHandle* vals,
                           const NDArrayHandle* /*row_ids*/,
                           int /*priority*/) {
  return MXKVStorePull(handle, num, keys, vals, 0);
}

int MXKVStorePullRowSparseEx(KVStoreHandle handle, mx_uint num,
                             const char** keys, NDArrayHandle* vals,
                             const NDArrayHandle* /*row_ids*/,
                             int /*priority*/) {
  return MXKVStorePullEx(handle, num, keys, vals, 0);
}

int MXKVStorePullWithSparse(KVStoreHandle handle, mx_uint num,
                            const int* keys, NDArrayHandle* vals,
                            int /*priority*/, bool /*ignore_sparse*/) {
  return MXKVStorePull(handle, num, keys, vals, 0);
}

int MXKVStorePullWithSparseEx(KVStoreHandle handle, mx_uint num,
                              const char** keys, NDArrayHandle* vals,
                              int /*priority*/, bool /*ignore_sparse*/) {
  return MXKVStorePullEx(handle, num, keys, vals, 0);
}

// -- engine push -----------------------------------------------------------

typedef void (*EngineSyncFunc)(void* rctx, void* const* const_vars,
                               void* const* mutate_vars);
typedef void (*EngineAsyncFunc)(void* rctx, void* on_complete_param,
                                void* const* const_vars,
                                void* const* mutate_vars);
typedef void (*EngineFuncParamDeleter)(void* param);

static void engine_noop_complete(void*) {}

int MXEnginePushSync(EngineSyncFunc sync_func, void* func_param,
                     void* deleter, void* /*ctx_handle*/,
                     void* const* const_vars_handle, int /*num_const_vars*/,
                     void* const* mutate_vars_handle,
                     int /*num_mutate_vars*/, void* /*prop_handle*/,
                     int /*priority*/, const char* /*opr_name*/) {
  // the execution engine is synchronous at the host level (XLA owns
  // async device work): run the function inline — identical observable
  // semantics to the reference's dependency-ordered push
  if (!sync_func) {
    g_last_error = "MXEnginePushSync: null function";
    return -1;
  }
  sync_func(func_param, const_vars_handle, mutate_vars_handle);
  if (deleter)
    reinterpret_cast<EngineFuncParamDeleter>(deleter)(func_param);
  return 0;
}

int MXEnginePushAsync(EngineAsyncFunc async_func, void* func_param,
                      void* deleter, void* /*ctx_handle*/,
                      void* const* const_vars_handle,
                      int /*num_const_vars*/,
                      void* const* mutate_vars_handle,
                      int /*num_mutate_vars*/, void* /*prop_handle*/,
                      int /*priority*/, const char* /*opr_name*/,
                      bool /*wait*/) {
  if (!async_func) {
    g_last_error = "MXEnginePushAsync: null function";
    return -1;
  }
  // the inline engine completes immediately: hand the function a VALID
  // no-op completion callback (conforming callers invoke it)
  async_func(func_param, reinterpret_cast<void*>(&engine_noop_complete),
             const_vars_handle, mutate_vars_handle);
  if (deleter)
    reinterpret_cast<EngineFuncParamDeleter>(deleter)(func_param);
  return 0;
}

}  // extern "C"

// ===========================================================================
// Round-4 third wave: Ex shape inference, C callbacks, raw data, and the
// CUDA-less Rtc/legacy-Func surfaces (reference parity: a reference
// build without USE_CUDA fails these the same way)
// ===========================================================================

extern "C" {

int MXSymbolInferShapeEx(SymbolHandle sym, mx_uint num_args,
                         const char** keys, const mx_uint* arg_ind_ptr,
                         const int* arg_shape_data,
                         mx_uint* in_shape_size, const int** in_shape_ndim,
                         const int*** in_shape_data, mx_uint* out_shape_size,
                         const int** out_shape_ndim,
                         const int*** out_shape_data, mx_uint* aux_shape_size,
                         const int** aux_shape_ndim,
                         const int*** aux_shape_data, int* complete) {
  // run the unsigned-shape implementation, then view the stores as int
  // (the backing vectors hold small positive dims)
  mx_uint total = num_args ? arg_ind_ptr[num_args] : 0;
  std::vector<mx_uint> u(total);
  for (mx_uint i = 0; i < total; ++i)
    u[i] = arg_shape_data[i] < 0 ? 0u   // -1 = unknown -> 0 marker
                                 : (mx_uint)arg_shape_data[i];
  mx_uint sizes[3];
  const mx_uint* ndims[3];
  const mx_uint** datas[3];
  int rc = MXSymbolInferShape(sym, num_args, keys, arg_ind_ptr, u.data(),
                              &sizes[0], &ndims[0], &datas[0], &sizes[1],
                              &ndims[1], &datas[1], &sizes[2], &ndims[2],
                              &datas[2], complete);
  if (rc != 0) return rc;
  *in_shape_size = sizes[0];
  *out_shape_size = sizes[1];
  *aux_shape_size = sizes[2];
  *in_shape_ndim = reinterpret_cast<const int*>(ndims[0]);
  *out_shape_ndim = reinterpret_cast<const int*>(ndims[1]);
  *aux_shape_ndim = reinterpret_cast<const int*>(ndims[2]);
  *in_shape_data = reinterpret_cast<const int**>(datas[0]);
  *out_shape_data = reinterpret_cast<const int**>(datas[1]);
  *aux_shape_data = reinterpret_cast<const int**>(datas[2]);
  return 0;
}

int MXSymbolInferShapePartialEx(
    SymbolHandle sym, mx_uint num_args, const char** keys,
    const mx_uint* arg_ind_ptr, const int* arg_shape_data,
    mx_uint* in_shape_size, const int** in_shape_ndim,
    const int*** in_shape_data, mx_uint* out_shape_size,
    const int** out_shape_ndim, const int*** out_shape_data,
    mx_uint* aux_shape_size, const int** aux_shape_ndim,
    const int*** aux_shape_data, int* complete) {
  mx_uint total = num_args ? arg_ind_ptr[num_args] : 0;
  std::vector<mx_uint> u(total);
  for (mx_uint i = 0; i < total; ++i)
    u[i] = arg_shape_data[i] < 0 ? 0u   // -1 = unknown -> 0 marker
                                 : (mx_uint)arg_shape_data[i];
  mx_uint sizes[3];
  const mx_uint* ndims[3];
  const mx_uint** datas[3];
  int rc = MXSymbolInferShapePartial(
      sym, num_args, keys, arg_ind_ptr, u.data(), &sizes[0], &ndims[0],
      &datas[0], &sizes[1], &ndims[1], &datas[1], &sizes[2], &ndims[2],
      &datas[2], complete);
  if (rc != 0) return rc;
  *in_shape_size = sizes[0];
  *out_shape_size = sizes[1];
  *aux_shape_size = sizes[2];
  *in_shape_ndim = reinterpret_cast<const int*>(ndims[0]);
  *out_shape_ndim = reinterpret_cast<const int*>(ndims[1]);
  *aux_shape_ndim = reinterpret_cast<const int*>(ndims[2]);
  *in_shape_data = reinterpret_cast<const int**>(datas[0]);
  *out_shape_data = reinterpret_cast<const int**>(datas[1]);
  *aux_shape_data = reinterpret_cast<const int**>(datas[2]);
  return 0;
}

// -- monitor / updater callbacks ------------------------------------------

typedef void (*ExecutorMonitorCallback)(const char*, NDArrayHandle, void*);

static int set_monitor_impl(ExecutorHandle handle,
                            ExecutorMonitorCallback callback,
                            void* callback_handle, int monitor_all) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OKKi)", reinterpret_cast<PyObject*>(handle),
      (unsigned long long)(uintptr_t)callback,
      (unsigned long long)(uintptr_t)callback_handle, monitor_all);
  return simple("executor_set_monitor", args);
}

int MXExecutorSetMonitorCallback(ExecutorHandle handle,
                                 ExecutorMonitorCallback callback,
                                 void* callback_handle) {
  return set_monitor_impl(handle, callback, callback_handle, 0);
}

int MXExecutorSetMonitorCallbackEX(ExecutorHandle handle,
                                   ExecutorMonitorCallback callback,
                                   void* callback_handle,
                                   bool monitor_all) {
  return set_monitor_impl(handle, callback, callback_handle,
                          monitor_all ? 1 : 0);
}

typedef void (*MXKVStoreUpdater)(int, NDArrayHandle, NDArrayHandle, void*);
typedef void (*MXKVStoreStrUpdater)(const char*, NDArrayHandle,
                                    NDArrayHandle, void*);

int MXKVStoreSetUpdaterEx(KVStoreHandle handle,
                          MXKVStoreUpdater updater,
                          MXKVStoreStrUpdater str_updater,
                          void* updater_handle) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OKKK)", reinterpret_cast<PyObject*>(handle),
      (unsigned long long)(uintptr_t)updater,
      (unsigned long long)(uintptr_t)str_updater,
      (unsigned long long)(uintptr_t)updater_handle);
  return simple("kvstore_set_updater", args);
}

int MXKVStoreSetUpdater(KVStoreHandle handle, MXKVStoreUpdater updater,
                        void* updater_handle) {
  return MXKVStoreSetUpdaterEx(handle, updater, nullptr, updater_handle);
}

// -- raw data --------------------------------------------------------------

int MXNDArrayGetData(NDArrayHandle handle, void** out_pdata) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  PyObject* bytes = call("ndarray_host_bytes", args);
  Py_DECREF(args);
  if (!bytes) { set_error_from_python(); return -1; }
  char* buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(bytes, &buf, &n) != 0) {
    PyErr_Clear();
    Py_DECREF(bytes);
    g_last_error = "MXNDArrayGetData: bridge returned non-bytes";
    return -1;
  }
  // READ-ONLY host snapshot with per-thread return-store lifetime
  // (valid until the next string/bytes-returning call on this thread).
  // Unlike the reference's live CPU buffer, writes through this
  // pointer do NOT reach the device array — use
  // MXNDArraySyncCopyFromCPU to mutate.
  g_ret.text.assign(buf, n);
  Py_DECREF(bytes);
  *out_pdata = const_cast<char*>(g_ret.text.data());
  return 0;
}

// -- Rtc family: reference parity for a CUDA-less build --------------------

static int rtc_unavailable() {
  g_last_error = "Rtc requires CUDA, which this TPU build does not have "
                 "(same failure as a reference build without USE_CUDA); "
                 "write accelerator kernels with Pallas instead "
                 "(docs/OP_PLUGINS.md)";
  return -1;
}

int MXRtcCreate(char*, mx_uint, mx_uint, char**, char**, NDArrayHandle*,
                NDArrayHandle*, char*, void** /*out*/) {
  return rtc_unavailable();
}
int MXRtcPush(void*, mx_uint, mx_uint, NDArrayHandle*, NDArrayHandle*,
              mx_uint, mx_uint, mx_uint, mx_uint, mx_uint, mx_uint) {
  return rtc_unavailable();
}
int MXRtcFree(void*) { return rtc_unavailable(); }
int MXRtcCudaModuleCreate(const char*, int, const char**, void**) {
  return rtc_unavailable();
}
int MXRtcCudaModuleFree(void*) { return rtc_unavailable(); }
int MXRtcCudaKernelCreate(void*, const char*, int, int*, int*, int*,
                          void**) {
  return rtc_unavailable();
}
int MXRtcCudaKernelFree(void*) { return rtc_unavailable(); }
int MXRtcCudaKernelCall(void*, int, void**, mx_uint, mx_uint, mx_uint,
                        mx_uint, mx_uint, mx_uint) {
  return rtc_unavailable();
}

// -- legacy NDArrayFunction registry (empty on this backend) ---------------

static int func_registry_empty() {
  g_last_error = "the legacy NDArrayFunction registry is empty on this "
                 "backend: every op is an imperative op "
                 "(MXImperativeInvoke / MXListAllOpNames)";
  return -1;
}

int MXFuncDescribe(void*, mx_uint*, mx_uint*, mx_uint*, int*) {
  return func_registry_empty();
}
int MXFuncGetInfo(void*, const char**, const char**, mx_uint*,
                  const char***, const char***, const char***,
                  const char**) {
  return func_registry_empty();
}
int MXFuncInvoke(void*, NDArrayHandle*, float*, NDArrayHandle*) {
  return func_registry_empty();
}
int MXFuncInvokeEx(void*, NDArrayHandle*, float*, NDArrayHandle*, int,
                   char**, char**) {
  return func_registry_empty();
}

}  // extern "C"

// ===========================================================================
// DLPack interchange (reference: c_api.cc MXNDArrayToDLPack family over
// include/mxnet/tensor_blob.h DLTensor). The struct layout below is the
// stable DLPack v0.x ABI other frameworks consume.
// ===========================================================================

extern "C" {

typedef struct {
  int device_type;   // kDLCPU = 1
  int device_id;
} DLPackDevice;

typedef struct {
  uint8_t code;
  uint8_t bits;
  uint16_t lanes;
} DLPackDataType;

typedef struct {
  void* data;
  DLPackDevice device;
  int ndim;
  DLPackDataType dtype;
  long long* shape;
  long long* strides;
  unsigned long long byte_offset;
} DLPackTensor;

struct DLPackManaged {
  DLPackTensor dl_tensor;
  void* manager_ctx;
  void (*deleter)(struct DLPackManaged*);
};

struct DLPackStorage {
  DLPackManaged managed;
  std::string bytes;
  std::vector<long long> shape;
};

static void dlpack_deleter(DLPackManaged* m) {
  if (m) delete reinterpret_cast<DLPackStorage*>(m->manager_ctx);
}

int MXNDArrayToDLPack(NDArrayHandle handle, void** out_dlpack) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  PyObject* tup = call("ndarray_dlpack_export", args);
  Py_DECREF(args);
  if (!tup) { set_error_from_python(); return -1; }
  char* buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(PyTuple_GetItem(tup, 0), &buf, &n) != 0) {
    PyErr_Clear();
    Py_DECREF(tup);
    g_last_error = "DLPack export: bridge returned non-bytes";
    return -1;
  }
  DLPackStorage* st = new DLPackStorage();
  st->bytes.assign(buf, n);
  PyObject* shp = PyTuple_GetItem(tup, 1);
  for (Py_ssize_t i = 0; i < PyList_Size(shp); ++i)
    st->shape.push_back(PyLong_AsLongLong(PyList_GetItem(shp, i)));
  long code = PyLong_AsLong(PyTuple_GetItem(tup, 2));
  long bits = PyLong_AsLong(PyTuple_GetItem(tup, 3));
  Py_DECREF(tup);
  st->managed.dl_tensor.data = const_cast<char*>(st->bytes.data());
  st->managed.dl_tensor.device = {1 /*kDLCPU*/, 0};
  st->managed.dl_tensor.ndim = (int)st->shape.size();
  st->managed.dl_tensor.dtype = {(uint8_t)code, (uint8_t)bits, 1};
  st->managed.dl_tensor.shape = st->shape.data();
  st->managed.dl_tensor.strides = nullptr;   // compact row-major
  st->managed.dl_tensor.byte_offset = 0;
  st->managed.manager_ctx = st;
  st->managed.deleter = &dlpack_deleter;
  *out_dlpack = &st->managed;
  return 0;
}

int MXNDArrayFromDLPack(void* dlpack, NDArrayHandle* out_nd) {
  ensure_python();
  Gil gil;
  DLPackManaged* m = reinterpret_cast<DLPackManaged*>(dlpack);
  if (!m || !m->dl_tensor.data) {
    g_last_error = "MXNDArrayFromDLPack: null tensor";
    return -1;
  }
  const DLPackTensor& t = m->dl_tensor;
  if (t.device.device_type != 1 /*kDLCPU*/) {
    g_last_error = "MXNDArrayFromDLPack: only kDLCPU tensors are "
                   "accepted (export your tensor to host first)";
    return -1;
  }
  if (t.strides != nullptr) {
    // verify compact row-major; anything else needs a host repack
    long long expect = 1;
    for (int i = t.ndim - 1; i >= 0; --i) {
      if (t.strides[i] != expect) {
        g_last_error = "MXNDArrayFromDLPack: non-contiguous strides "
                       "are not supported";
        return -1;
      }
      expect *= t.shape[i];
    }
  }
  long long count = 1;
  PyObject* shp = PyList_New(t.ndim);
  for (int i = 0; i < t.ndim; ++i) {
    PyList_SetItem(shp, i, PyLong_FromLongLong(t.shape[i]));
    count *= t.shape[i];
  }
  Py_ssize_t nbytes = (Py_ssize_t)(count * (t.dtype.bits / 8));
  PyObject* b = PyBytes_FromStringAndSize(
      (const char*)t.data + t.byte_offset, nbytes);
  PyObject* args = Py_BuildValue("(OOii)", b, shp, (int)t.dtype.code,
                                 (int)t.dtype.bits);
  Py_DECREF(b);
  Py_DECREF(shp);
  return out_handle("ndarray_dlpack_import", args, out_nd);
}

int MXNDArrayCallDLPackDeleter(void* dlpack) {
  DLPackManaged* m = reinterpret_cast<DLPackManaged*>(dlpack);
  if (m && m->deleter) m->deleter(m);
  return 0;
}

}  // extern "C"

extern "C" {

int MXAutogradGetSymbol(NDArrayHandle handle, SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  return out_handle("autograd_get_symbol", args, out);
}

}  // extern "C"

extern "C" {

typedef int (*CustomOpPropCreator)(const char*, const int, const char**,
                                   const char**, void*);

int MXCustomOpRegister(const char* op_type, CustomOpPropCreator creator) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(sK)", op_type, (unsigned long long)(uintptr_t)creator);
  return simple("custom_op_register", args);
}

}  // extern "C"

extern "C" {

struct MXCallbackListDecl {
  int num_callbacks;
  int (**callbacks)(void);
  void** contexts;
};

int MXCustomFunctionRecord(int num_inputs, NDArrayHandle* inputs,
                           int num_outputs, NDArrayHandle* outputs,
                           struct MXCallbackListDecl* callbacks) {
  Gil gil;
  if (!callbacks || callbacks->num_callbacks < 1) {
    g_last_error = "MXCustomFunctionRecord: missing backward callback "
                   "(enum kCustomFunctionBackward slot 0)";
    return -1;
  }
  PyObject* ins = make_handle_list((unsigned)num_inputs, inputs);
  PyObject* outs = make_handle_list((unsigned)num_outputs, outputs);
  PyObject* args = Py_BuildValue(
      "(OOKK)", ins, outs,
      (unsigned long long)(uintptr_t)callbacks->callbacks[0],
      (unsigned long long)(uintptr_t)(callbacks->contexts
                                          ? callbacks->contexts[0]
                                          : nullptr));
  Py_DECREF(ins);
  Py_DECREF(outs);
  return simple("custom_function_record", args);
}

}  // extern "C"

extern "C" {

int MXSymbolCutSubgraph(SymbolHandle sym, SymbolHandle** input_symbols,
                        int* input_size) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", reinterpret_cast<PyObject*>(sym));
  return out_handle_list("symbol_cut_subgraph", args, input_size,
                         reinterpret_cast<void***>(input_symbols));
}

}  // extern "C"
