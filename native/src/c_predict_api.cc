// C predict API — the minimal inference ABI for host applications
// (reference: include/mxnet/c_predict_api.h:78-233, implementation
// src/c_api/c_predict_api.cc — MXPredCreate/SetInput/Forward/
// GetOutputShape/GetOutput/Free, MXGetLastError).
//
// TPU-native inversion: the reference wraps a C++ executor for Python;
// here the runtime IS Python/XLA, so this library embeds CPython and
// drives mxnet_tpu.native.predict_bridge. C callers get the same ABI
// either standalone (the library initializes an interpreter) or inside
// an existing Python process (ctypes load: the running interpreter is
// reused; every entry point takes the GIL via PyGILState).

#include <Python.h>

#include <cstring>
#include <mutex>
#include <string>
#include <vector>

extern "C" {
typedef void* PredictorHandle;

int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 unsigned num_input_nodes, const char** input_keys,
                 const unsigned* input_shape_indptr,
                 const unsigned* input_shape_data, PredictorHandle* out);
int MXPredSetInput(PredictorHandle handle, const char* key,
                   const float* data, unsigned size);
int MXPredForward(PredictorHandle handle);
int MXPredGetOutputShape(PredictorHandle handle, unsigned index,
                         unsigned** shape_data, unsigned* shape_ndim);
int MXPredGetOutput(PredictorHandle handle, unsigned index, float* data,
                    unsigned size);
int MXPredFree(PredictorHandle handle);
const char* MXGetLastError();
int mxpred_abi_version();
}

namespace {

thread_local std::string g_last_error;

struct Predictor {
  PyObject* obj;                       // bridge _Predictor
  std::vector<unsigned> shape_buf;     // backing store for GetOutputShape
};

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error = "python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c) g_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// Ensure an interpreter exists (standalone C host) exactly once.
void ensure_python() {
  static std::once_flag once;
  std::call_once(once, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
#if PY_VERSION_HEX < 0x03090000
      PyEval_InitThreads();
#endif
      // release the GIL acquired by Py_Initialize so PyGILState_Ensure
      // works from any thread
      PyEval_SaveThread();
    }
  });
}

PyObject* bridge() {  // borrowed-style: cached, never released
  static PyObject* mod = nullptr;
  if (!mod) mod = PyImport_ImportModule("mxnet_tpu.native.predict_bridge");
  return mod;
}

struct Gil {
  PyGILState_STATE st;
  Gil() { st = PyGILState_Ensure(); }
  ~Gil() { PyGILState_Release(st); }
};

}  // namespace

extern "C" {

int mxpred_abi_version() { return 1; }

const char* MXGetLastError() { return g_last_error.c_str(); }

int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 unsigned num_input_nodes, const char** input_keys,
                 const unsigned* input_shape_indptr,
                 const unsigned* input_shape_data, PredictorHandle* out) {
  (void)dev_type;  // one logical accelerator context under XLA
  (void)dev_id;
  ensure_python();
  Gil gil;
  PyObject* mod = bridge();
  if (!mod) { set_error_from_python(); return -1; }

  PyObject* names = PyList_New(num_input_nodes);
  PyObject* shapes = PyList_New(num_input_nodes);
  for (unsigned i = 0; i < num_input_nodes; ++i) {
    PyList_SET_ITEM(names, i, PyUnicode_FromString(input_keys[i]));
    unsigned lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject* shp = PyList_New(hi - lo);
    for (unsigned j = lo; j < hi; ++j)
      PyList_SET_ITEM(shp, j - lo, PyLong_FromUnsignedLong(
          input_shape_data[j]));
    PyList_SET_ITEM(shapes, i, shp);
  }
  PyObject* params = PyBytes_FromStringAndSize(
      static_cast<const char*>(param_bytes), param_size);
  PyObject* res = PyObject_CallMethod(
      mod, "create", "sOOO",
      symbol_json_str ? symbol_json_str : "", params, names, shapes);
  Py_DECREF(params);
  Py_DECREF(names);
  Py_DECREF(shapes);
  if (!res) { set_error_from_python(); return -1; }
  Predictor* p = new Predictor{res, {}};
  *out = p;
  return 0;
}

int MXPredSetInput(PredictorHandle handle, const char* key,
                   const float* data, unsigned size) {
  Gil gil;
  Predictor* p = static_cast<Predictor*>(handle);
  PyObject* mv = PyMemoryView_FromMemory(
      reinterpret_cast<char*>(const_cast<float*>(data)),
      static_cast<Py_ssize_t>(size) * sizeof(float), PyBUF_READ);
  // bridge reshapes the flat f32 buffer onto the bound input
  PyObject* np = PyImport_ImportModule("numpy");
  PyObject* flat = np ? PyObject_CallMethod(np, "frombuffer", "Os", mv,
                                            "float32")
                      : nullptr;
  Py_XDECREF(np);
  Py_DECREF(mv);
  if (!flat) { set_error_from_python(); return -1; }
  PyObject* res = PyObject_CallMethod(bridge(), "set_input", "OsO",
                                      p->obj, key, flat);
  Py_DECREF(flat);
  if (!res) { set_error_from_python(); return -1; }
  Py_DECREF(res);
  return 0;
}

int MXPredForward(PredictorHandle handle) {
  Gil gil;
  Predictor* p = static_cast<Predictor*>(handle);
  PyObject* res = PyObject_CallMethod(bridge(), "forward", "O", p->obj);
  if (!res) { set_error_from_python(); return -1; }
  Py_DECREF(res);
  return 0;
}

int MXPredGetOutputShape(PredictorHandle handle, unsigned index,
                         unsigned** shape_data, unsigned* shape_ndim) {
  Gil gil;
  Predictor* p = static_cast<Predictor*>(handle);
  PyObject* res = PyObject_CallMethod(bridge(), "get_output_shape", "OI",
                                      p->obj, index);
  if (!res) { set_error_from_python(); return -1; }
  Py_ssize_t n = PyTuple_Size(res);
  p->shape_buf.resize(static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i)
    p->shape_buf[static_cast<size_t>(i)] = static_cast<unsigned>(
        PyLong_AsUnsignedLong(PyTuple_GET_ITEM(res, i)));
  Py_DECREF(res);
  *shape_data = p->shape_buf.data();
  *shape_ndim = static_cast<unsigned>(n);
  return 0;
}

int MXPredGetOutput(PredictorHandle handle, unsigned index, float* data,
                    unsigned size) {
  Gil gil;
  Predictor* p = static_cast<Predictor*>(handle);
  PyObject* res = PyObject_CallMethod(bridge(), "get_output", "OI",
                                      p->obj, index);
  if (!res) { set_error_from_python(); return -1; }
  PyObject* tobytes = PyObject_CallMethod(res, "tobytes", nullptr);
  Py_DECREF(res);
  if (!tobytes) { set_error_from_python(); return -1; }
  char* buf = nullptr;
  Py_ssize_t len = 0;
  PyBytes_AsStringAndSize(tobytes, &buf, &len);
  if (static_cast<size_t>(len) != static_cast<size_t>(size) *
      sizeof(float)) {
    Py_DECREF(tobytes);
    g_last_error = "MXPredGetOutput: size mismatch";
    return -1;
  }
  std::memcpy(data, buf, static_cast<size_t>(len));
  Py_DECREF(tobytes);
  return 0;
}

int MXPredFree(PredictorHandle handle) {
  Gil gil;
  Predictor* p = static_cast<Predictor*>(handle);
  Py_XDECREF(p->obj);
  delete p;
  return 0;
}

}  // extern "C"
