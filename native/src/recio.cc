// Native RecordIO engine (the TPU-native analog of the reference's C++
// dmlc-core recordio + src/io/ iterator runtime; see
// src/io/iter_image_recordio_2.cc for the threaded C++ pipeline this
// replaces). Python binds via ctypes (mxnet_tpu/native/__init__.py).
//
// On-disk framing (dmlc recordio, byte-compatible with im2rec output):
//   [kMagic u32][lrec u32][payload ... padded to 4B]
//   lrec = cflag<<29 | length  (cflag!=0 marks continuation chunks)
//
// Exposed C surface:
//   recio_scan     — offsets of every record (mmap-speed, no Python loop)
//   recio_read_batch — pread a batch of records into one packed buffer
//   recio_reader_* — a background-thread prefetching batch reader with
//                    epoch shuffling (bounded queue, like PrefetcherIter)

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <queue>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

struct Rec {
  int64_t off;   // offset of payload (past magic+lrec)
  int64_t len;   // payload length
};

// scan result: >=0 ok, -1 io error, -2 corrupt framing, -3 contains
// multi-chunk records (cflag!=0; callers fall back to the python reader,
// which reassembles them — they only occur for >=2^29-byte payloads)
int scan_records(const char* path, std::vector<Rec>* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  std::fseek(f, 0, SEEK_END);
  const int64_t fsize = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  int64_t pos = 0;
  uint32_t hdr[2];
  int rc = 0;
  while (pos + 8 <= fsize) {
    std::fseek(f, pos, SEEK_SET);
    if (std::fread(hdr, 4, 2, f) != 2) {
      rc = -2;  // header promised by file size but unreadable
      break;
    }
    if (hdr[0] != kMagic) {
      rc = -2;  // corrupt framing is an error, not a silent EOF
      break;
    }
    const uint32_t cflag = hdr[1] >> 29;
    const int64_t len = hdr[1] & ((1u << 29) - 1);
    if (cflag != 0) {
      rc = -3;
      break;
    }
    out->push_back({pos + 8, len});
    // skip payload + 4-byte padding
    pos += 8 + ((len + 3) / 4) * 4;
  }
  std::fclose(f);
  return rc;
}

struct Batch {
  std::vector<char> buf;
  std::vector<int64_t> sizes;
  bool last = false;
};

class Reader {
 public:
  Reader(const char* path, int batch, int shuffle, uint64_t seed,
         int prefetch)
      : path_(path), batch_(batch), shuffle_(shuffle), rng_(seed),
        prefetch_(std::max(prefetch, 1)) {
    ok_ = scan_records(path_.c_str(), &recs_) == 0;
    order_.resize(recs_.size());
    for (size_t i = 0; i < recs_.size(); ++i) order_[i] = i;
    if (ok_) start();
  }

  ~Reader() { stop(); }

  bool ok() const { return ok_; }
  int64_t num_records() const { return static_cast<int64_t>(recs_.size()); }

  void reset() {
    stop();
    start();
  }

  // Pops the next batch; returns number of records, 0 = epoch end (the
  // sentinel stays queued so repeated polls keep returning 0 until
  // reset), or -needed_bytes when the caller's buffer is too small (the
  // batch stays queued for the retry). Payloads pack back to back.
  int64_t next(char* buf, int64_t cap, int64_t* sizes) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_pop_.wait(lk, [&] { return !queue_.empty(); });
    Batch& front = queue_.front();
    if (front.last) return 0;
    const int64_t need = static_cast<int64_t>(front.buf.size());
    if (need > cap) return -need;
    Batch b = std::move(front);
    queue_.pop();
    cv_push_.notify_one();
    lk.unlock();
    std::memcpy(buf, b.buf.data(), b.buf.size());
    for (size_t i = 0; i < b.sizes.size(); ++i) sizes[i] = b.sizes[i];
    return static_cast<int64_t>(b.sizes.size());
  }

 private:
  void start() {
    done_ = false;
    if (shuffle_) {
      std::shuffle(order_.begin(), order_.end(), rng_);
    }
    worker_ = std::thread([this] { produce(); });
  }

  void stop() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      done_ = true;
      // drain so a blocked producer wakes
      while (!queue_.empty()) queue_.pop();
    }
    cv_push_.notify_all();
    if (worker_.joinable()) worker_.join();
    std::lock_guard<std::mutex> lk(mu_);
    while (!queue_.empty()) queue_.pop();
  }

  void produce() {
    FILE* f = std::fopen(path_.c_str(), "rb");
    if (!f) return push_last();
    const size_t n = order_.size();
    for (size_t i = 0; i < n; i += batch_) {
      Batch b;
      const size_t hi = std::min(n, i + batch_);
      for (size_t j = i; j < hi; ++j) {
        const Rec& r = recs_[order_[j]];
        const size_t base = b.buf.size();
        b.buf.resize(base + r.len);
        std::fseek(f, r.off, SEEK_SET);
        if (std::fread(b.buf.data() + base, 1, r.len, f) !=
            static_cast<size_t>(r.len)) {
          std::fclose(f);
          return push_last();
        }
        b.sizes.push_back(r.len);
      }
      std::unique_lock<std::mutex> lk(mu_);
      cv_push_.wait(lk, [&] {
        return done_ || queue_.size() < static_cast<size_t>(prefetch_);
      });
      if (done_) {
        std::fclose(f);
        return;
      }
      queue_.push(std::move(b));
      cv_pop_.notify_one();
    }
    std::fclose(f);
    push_last();
  }

  void push_last() {
    std::lock_guard<std::mutex> lk(mu_);
    Batch b;
    b.last = true;
    queue_.push(std::move(b));
    cv_pop_.notify_one();
  }

  std::string path_;
  int batch_;
  int shuffle_;
  std::mt19937_64 rng_;
  int prefetch_;
  bool ok_ = false;
  bool done_ = false;
  std::vector<Rec> recs_;
  std::vector<size_t> order_;
  std::queue<Batch> queue_;
  std::mutex mu_;
  std::condition_variable cv_push_, cv_pop_;
  std::thread worker_;
};

}  // namespace

extern "C" {

// Scan record payload offsets+lengths. Pass offsets==nullptr to count.
// Returns record count, or -1 on IO error.
int64_t recio_scan(const char* path, int64_t* offsets, int64_t* lengths,
                   int64_t max_n) {
  std::vector<Rec> recs;
  const int rc = scan_records(path, &recs);
  if (rc != 0) return rc;
  if (offsets) {
    const int64_t n =
        std::min<int64_t>(max_n, static_cast<int64_t>(recs.size()));
    for (int64_t i = 0; i < n; ++i) {
      offsets[i] = recs[i].off;
      lengths[i] = recs[i].len;
    }
  }
  return static_cast<int64_t>(recs.size());
}

// Read n records (given payload offsets/lengths) into one packed buffer.
// Returns total bytes written, or -1 on error / insufficient capacity.
int64_t recio_read_batch(const char* path, const int64_t* offsets,
                         const int64_t* lengths, int64_t n, char* buf,
                         int64_t cap) {
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) total += lengths[i];
  if (total > cap) return -1;
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  int64_t w = 0;
  for (int64_t i = 0; i < n; ++i) {
    std::fseek(f, offsets[i], SEEK_SET);
    if (std::fread(buf + w, 1, lengths[i], f) !=
        static_cast<size_t>(lengths[i])) {
      std::fclose(f);
      return -1;
    }
    w += lengths[i];
  }
  std::fclose(f);
  return w;
}

void* recio_reader_create(const char* path, int batch, int shuffle,
                          uint64_t seed, int prefetch) {
  Reader* r = new Reader(path, batch, shuffle, seed, prefetch);
  if (!r->ok()) {
    delete r;
    return nullptr;
  }
  return r;
}

int64_t recio_reader_num_records(void* h) {
  return static_cast<Reader*>(h)->num_records();
}

int64_t recio_reader_next(void* h, char* buf, int64_t cap,
                          int64_t* sizes) {
  return static_cast<Reader*>(h)->next(buf, cap, sizes);
}

void recio_reader_reset(void* h) { static_cast<Reader*>(h)->reset(); }

void recio_reader_free(void* h) { delete static_cast<Reader*>(h); }

int recio_abi_version() { return 2; }

}  // extern "C"
