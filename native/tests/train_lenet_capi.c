/* End-to-end LeNet-style training purely through the C ABI
 * (libmxcapi.so): MXDataIterCreateIter(MNISTIter) feeds batches,
 * MXImperativeInvoke runs the forward ops, MXAutogradMarkVariables /
 * MXAutogradBackward produce gradients, and sgd_update applies them
 * in place — no Python in this translation unit. The reference analog
 * is a from-scratch C binding driving c_api.h the way the Scala/Julia
 * frontends do.
 *
 * Usage: train_lenet_capi <images.idx> <labels.idx>
 * Exit 0 iff the final epoch's loss is well below the first batch's.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* NDArrayHandle;
typedef void* AtomicSymbolCreator;
typedef void* DataIterCreator;
typedef void* DataIterHandle;
typedef unsigned mx_uint;

extern const char* MXGetLastError();
extern int MXNDArrayCreateEx(const mx_uint*, mx_uint, int, int, int, int,
                             NDArrayHandle*);
extern int MXNDArrayFree(NDArrayHandle);
extern int MXNDArraySyncCopyFromCPU(NDArrayHandle, const void*, size_t);
extern int MXNDArraySyncCopyToCPU(NDArrayHandle, void*, size_t);
extern int MXNDArrayGetShape(NDArrayHandle, mx_uint*, const mx_uint**);
extern int MXSymbolListAtomicSymbolCreators(mx_uint*,
                                            AtomicSymbolCreator**);
extern int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator, const char**);
extern int MXImperativeInvoke(AtomicSymbolCreator, int, NDArrayHandle*,
                              int*, NDArrayHandle**, int, const char**,
                              const char**);
extern int MXAutogradSetIsRecording(int, int*);
extern int MXAutogradSetIsTraining(int, int*);
extern int MXAutogradMarkVariables(mx_uint, NDArrayHandle*, mx_uint*,
                                   NDArrayHandle*);
extern int MXAutogradBackward(mx_uint, NDArrayHandle*, NDArrayHandle*,
                              int);
extern int MXListDataIters(mx_uint*, DataIterCreator**);
extern int MXDataIterGetIterInfo(DataIterCreator, const char**,
                                 const char**, mx_uint*, const char***,
                                 const char***, const char***);
extern int MXDataIterCreateIter(DataIterCreator, mx_uint, const char**,
                                const char**, DataIterHandle*);
extern int MXDataIterNext(DataIterHandle, int*);
extern int MXDataIterBeforeFirst(DataIterHandle);
extern int MXDataIterGetData(DataIterHandle, NDArrayHandle*);
extern int MXDataIterGetLabel(DataIterHandle, NDArrayHandle*);
extern int MXDataIterFree(DataIterHandle);
extern int MXNDArrayWaitAll();

#ifdef __cplusplus
}  /* extern "C" */
#endif

#define CHECK(stmt) do { \
    if ((stmt) != 0) { \
      fprintf(stderr, "FAILED %s: %s\n", #stmt, MXGetLastError()); \
      exit(2); \
    } \
  } while (0)

static AtomicSymbolCreator find_op(const char* want) {
  static AtomicSymbolCreator* creators = NULL;
  static mx_uint n = 0;
  if (!creators) CHECK(MXSymbolListAtomicSymbolCreators(&n, &creators));
  /* creators stay valid: the library interns them for process life;
     copy the array since the return store is reused per call */
  static AtomicSymbolCreator saved[4096];
  static int saved_init = 0;
  if (!saved_init) {
    if (n > 4096) {
      fprintf(stderr, "op registry larger than creator cache\n");
      exit(2);
    }
    memcpy(saved, creators, n * sizeof(*creators));
    saved_init = 1;
  }
  for (mx_uint i = 0; i < n; ++i) {
    const char* name = NULL;
    CHECK(MXSymbolGetAtomicSymbolName(saved[i], &name));
    if (name && strcmp(name, want) == 0) return saved[i];
  }
  fprintf(stderr, "op %s not found\n", want);
  exit(2);
}

/* invoke with allocated outputs: returns first output handle */
static NDArrayHandle invoke1(const char* op, int nin, NDArrayHandle* in,
                             int nparam, const char** keys,
                             const char** vals) {
  int nout = 0;
  NDArrayHandle* outs = NULL;
  CHECK(MXImperativeInvoke(find_op(op), nin, in, &nout, &outs, nparam,
                           keys, vals));
  NDArrayHandle h = outs[0];
  return h;
}

/* invoke writing into dst (the in-place mode) */
static void invoke_into(const char* op, int nin, NDArrayHandle* in,
                        NDArrayHandle dst, int nparam, const char** keys,
                        const char** vals) {
  int nout = 1;
  NDArrayHandle outs_store[1];
  NDArrayHandle* outs = outs_store;
  outs_store[0] = dst;
  CHECK(MXImperativeInvoke(find_op(op), nin, in, &nout, &outs, nparam,
                           keys, vals));
}

static unsigned long long rng_state = 0x9E3779B97F4A7C15ull;
static float frand(void) {      /* xorshift uniform in [-1, 1) */
  rng_state ^= rng_state << 13;
  rng_state ^= rng_state >> 7;
  rng_state ^= rng_state << 17;
  return ((float)((rng_state >> 11) & 0xFFFFFF) / 8388608.0f) - 1.0f;
}

static NDArrayHandle make_param(mx_uint* shape, mx_uint ndim, float scale) {
  NDArrayHandle h;
  CHECK(MXNDArrayCreateEx(shape, ndim, 1 /*cpu*/, 0, 0, 0 /*f32*/, &h));
  size_t n = 1;
  for (mx_uint i = 0; i < ndim; ++i) n *= shape[i];
  float* buf = (float*)malloc(n * sizeof(float));
  for (size_t i = 0; i < n; ++i) buf[i] = scale * frand();
  CHECK(MXNDArraySyncCopyFromCPU(h, buf, n));
  free(buf);
  return h;
}

static NDArrayHandle make_zeros_like(NDArrayHandle src) {
  mx_uint ndim = 0;
  const mx_uint* shp = NULL;
  CHECK(MXNDArrayGetShape(src, &ndim, &shp));
  mx_uint copy[8];
  memcpy(copy, shp, ndim * sizeof(mx_uint));
  NDArrayHandle h;
  CHECK(MXNDArrayCreateEx(copy, ndim, 1, 0, 0, 0, &h));
  return h;
}

static float scalar_of(NDArrayHandle h) {
  float v = 0.0f;
  CHECK(MXNDArraySyncCopyToCPU(h, &v, 1));
  return v;
}

static double now_s(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (double)ts.tv_sec + 1e-9 * (double)ts.tv_nsec;
}

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s images.idx labels.idx\n", argv[0]);
    return 2;
  }
  const int BATCH = 32;

  /* ---- data iterator ---- */
  DataIterCreator mnist = NULL;
  mx_uint n_iters = 0;
  DataIterCreator* iters = NULL;
  CHECK(MXListDataIters(&n_iters, &iters));
  for (mx_uint i = 0; i < n_iters && !mnist; ++i) {
    const char *name = NULL, *desc = NULL;
    mx_uint na = 0;
    DataIterCreator c = iters[i];
    CHECK(MXDataIterGetIterInfo(c, &name, &desc, &na, NULL, NULL, NULL));
    if (strcmp(name, "MNISTIter") == 0) mnist = c;
  }
  if (!mnist) { fprintf(stderr, "MNISTIter missing\n"); return 2; }

  const char* ikeys[] = {"image", "label", "batch_size", "shuffle",
                         "flat"};
  const char* ivals[] = {argv[1], argv[2], "32", "0", "0"};
  DataIterHandle it = NULL;
  CHECK(MXDataIterCreateIter(mnist, 5, ikeys, ivals, &it));

  /* ---- parameters + gradients ---- */
  mx_uint s_convw[] = {8, 1, 3, 3}, s_convb[] = {8};
  mx_uint s_fc1w[] = {32, 8 * 14 * 14}, s_fc1b[] = {32};
  mx_uint s_fc2w[] = {10, 32}, s_fc2b[] = {10};
  NDArrayHandle params[6] = {
      make_param(s_convw, 4, 0.30f),  make_param(s_convb, 1, 0.0f),
      make_param(s_fc1w, 2, 0.05f),   make_param(s_fc1b, 1, 0.0f),
      make_param(s_fc2w, 2, 0.20f),   make_param(s_fc2b, 1, 0.0f)};
  NDArrayHandle grads[6];
  mx_uint reqs[6];
  for (int i = 0; i < 6; ++i) {
    grads[i] = make_zeros_like(params[i]);
    reqs[i] = 1; /* write */
  }
  CHECK(MXAutogradMarkVariables(6, params, reqs, grads));

  /* ---- training ---- */
  /* Per-epoch wall-clock budget + phase heartbeat: a stall reports
   * WHERE it is (iter / forward / backward / update / loss-fetch)
   * instead of silently eating the harness's 900 s subprocess budget
   * (round-5 VERDICT Weak #7). Budget env: MXNET_TPU_EPOCH_BUDGET_S,
   * 0 disables; exit code 3 is the budget-exceeded diagnosis. */
  double epoch_budget_s = 240.0;
  {
    const char* b = getenv("MXNET_TPU_EPOCH_BUDGET_S");
    if (b && *b) epoch_budget_s = atof(b);
  }
  float first_loss = -1.0f, loss = 0.0f;
  const char* lr_keys[] = {"lr", "rescale_grad"};
  const char* lr_vals[] = {"0.1", "0.03125"};  /* 1/BATCH */
  for (int epoch = 0; epoch < 3; ++epoch) {
    CHECK(MXDataIterBeforeFirst(it));
    int has = 0;
    float epoch_loss = 0.0f;
    int batches = 0;
    double t_epoch = now_s();
    double t_iter = 0, t_fwd = 0, t_bwd = 0, t_upd = 0, t_sync = 0;
    while (1) {
      double t0 = now_s(), t1;
      CHECK(MXDataIterNext(it, &has));
      if (!has) break;
      NDArrayHandle x = NULL, y = NULL;
      CHECK(MXDataIterGetData(it, &x));
      CHECK(MXDataIterGetLabel(it, &y));
      t1 = now_s(); t_iter += t1 - t0; t0 = t1;

      int prev = 0;
      CHECK(MXAutogradSetIsRecording(1, &prev));
      CHECK(MXAutogradSetIsTraining(1, &prev));

      const char* ck[] = {"kernel", "num_filter", "pad"};
      const char* cv[] = {"(3, 3)", "8", "(1, 1)"};
      NDArrayHandle conv_in[] = {x, params[0], params[1]};
      NDArrayHandle h1 = invoke1("Convolution", 3, conv_in, 3, ck, cv);

      const char* ak[] = {"act_type"};
      const char* av[] = {"relu"};
      NDArrayHandle h2 = invoke1("Activation", 1, &h1, 1, ak, av);

      const char* pk[] = {"kernel", "stride", "pool_type"};
      const char* pv[] = {"(2, 2)", "(2, 2)", "max"};
      NDArrayHandle h3 = invoke1("Pooling", 1, &h2, 3, pk, pv);

      NDArrayHandle h4 = invoke1("Flatten", 1, &h3, 0, NULL, NULL);

      const char* fk[] = {"num_hidden"};
      const char* f1v[] = {"32"};
      NDArrayHandle fc1_in[] = {h4, params[2], params[3]};
      NDArrayHandle h5 = invoke1("FullyConnected", 3, fc1_in, 1, fk, f1v);
      NDArrayHandle h6 = invoke1("Activation", 1, &h5, 1, ak, av);

      const char* f2v[] = {"10"};
      NDArrayHandle fc2_in[] = {h6, params[4], params[5]};
      NDArrayHandle h7 = invoke1("FullyConnected", 3, fc2_in, 1, fk, f2v);

      NDArrayHandle ce_in[] = {h7, y};
      NDArrayHandle l = invoke1("softmax_cross_entropy", 2, ce_in, 0,
                                NULL, NULL);
      t1 = now_s(); t_fwd += t1 - t0; t0 = t1;

      CHECK(MXAutogradSetIsRecording(0, &prev));
      CHECK(MXAutogradBackward(1, &l, NULL, 0));
      t1 = now_s(); t_bwd += t1 - t0; t0 = t1;

      for (int i = 0; i < 6; ++i) {
        NDArrayHandle upd_in[] = {params[i], grads[i]};
        invoke_into("sgd_update", 2, upd_in, params[i], 2, lr_keys,
                    lr_vals);
      }
      t1 = now_s(); t_upd += t1 - t0; t0 = t1;

      loss = scalar_of(l) / BATCH;
      t1 = now_s(); t_sync += t1 - t0;
      if (first_loss < 0.0f) first_loss = loss;
      epoch_loss += loss;
      ++batches;
      if (batches % 5 == 0) {
        printf("heartbeat epoch %d batch %d t=%.1fs "
               "(iter %.1f fwd %.1f bwd %.1f upd %.1f sync %.1f)\n",
               epoch, batches, now_s() - t_epoch, t_iter, t_fwd,
               t_bwd, t_upd, t_sync);
        fflush(stdout);
      }
      if (epoch_budget_s > 0 && now_s() - t_epoch > epoch_budget_s) {
        fprintf(stderr,
                "epoch %d exceeded %.0fs budget at batch %d: "
                "iter %.1fs fwd %.1fs bwd %.1fs upd %.1fs sync %.1fs "
                "— the dominant phase above is the stall site\n",
                epoch, epoch_budget_s, batches, t_iter, t_fwd, t_bwd,
                t_upd, t_sync);
        fflush(stderr);
        return 3;
      }

      NDArrayHandle tmp[] = {h1, h2, h3, h4, h5, h6, h7, l, x, y};
      for (int i = 0; i < 10; ++i) MXNDArrayFree(tmp[i]);
    }
    printf("epoch %d mean_loss %.4f (%d batches) wall %.1fs "
           "(iter %.1f fwd %.1f bwd %.1f upd %.1f sync %.1f)\n",
           epoch, epoch_loss / (batches > 0 ? batches : 1), batches,
           now_s() - t_epoch, t_iter, t_fwd, t_bwd, t_upd, t_sync);
    fflush(stdout);
  }
  CHECK(MXNDArrayWaitAll());
  printf("first_loss %.4f final_loss %.4f\n", first_loss, loss);
  CHECK(MXDataIterFree(it));
  for (int i = 0; i < 6; ++i) {
    MXNDArrayFree(params[i]);
    MXNDArrayFree(grads[i]);
  }
  if (!(loss < 0.6f * first_loss)) {
    fprintf(stderr, "loss did not decrease enough\n");
    return 1;
  }
  printf("OK\n");
  return 0;
}
