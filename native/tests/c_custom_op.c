/* A custom operator implemented in C and registered through
 * MXCustomOpRegister: 'caddone' computes out = in + 1 by driving the
 * MX imperative C API from inside its forward callback, and passes the
 * gradient straight through in backward. Exercises the reference
 * MXCallbackList protocol end-to-end from a compiled library. */
#include <stdio.h>
#include <string.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* NDArrayHandle;
typedef void* AtomicSymbolCreator;

extern int MXSymbolListAtomicSymbolCreators(unsigned*,
                                            AtomicSymbolCreator**);
extern int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator, const char**);
extern int MXImperativeInvoke(AtomicSymbolCreator, int, NDArrayHandle*,
                              int*, NDArrayHandle**, int, const char**,
                              const char**);
extern int MXNDArraySyncCopyFromNDArray(NDArrayHandle, NDArrayHandle, int);

struct MXCallbackList {
  int num_callbacks;
  int (**callbacks)(void);
  void** contexts;
};

static AtomicSymbolCreator find_op(const char* want) {
  unsigned n = 0;
  AtomicSymbolCreator* cs = NULL;
  if (MXSymbolListAtomicSymbolCreators(&n, &cs) != 0) return NULL;
  /* copy: the return store is reused by the name lookups below */
  static AtomicSymbolCreator saved[4096];
  if (n > 4096) return NULL;
  memcpy(saved, cs, n * sizeof(*cs));
  for (unsigned i = 0; i < n; ++i) {
    const char* name = NULL;
    if (MXSymbolGetAtomicSymbolName(saved[i], &name) == 0 && name &&
        strcmp(name, want) == 0)
      return saved[i];
  }
  return NULL;
}

/* ---- op callbacks (enum CustomOpCallbacks: del, fwd, bwd) ---- */

static int op_delete(void* state) { (void)state; return 1; }

static int op_forward(int size, void** ptrs, int* tags, const int* reqs,
                      const int is_train, void* state) {
  (void)reqs; (void)is_train; (void)state;
  NDArrayHandle in = NULL, out = NULL;
  for (int i = 0; i < size; ++i) {
    if (tags[i] == 0 && !in) in = ptrs[i];
    if (tags[i] == 1 && !out) out = ptrs[i];
  }
  if (!in || !out) return 0;
  AtomicSymbolCreator plus = find_op("_plus_scalar");
  if (!plus) return 0;
  const char* k[] = {"scalar"};
  const char* v[] = {"1.0"};
  NDArrayHandle outs_store[1] = {out};
  NDArrayHandle* outs = outs_store;
  int nout = 1;
  NDArrayHandle ins[] = {in};
  return MXImperativeInvoke(plus, 1, ins, &nout, &outs, 1, k, v) == 0
             ? 1 : 0;
}

static int op_backward(int size, void** ptrs, int* tags, const int* reqs,
                       const int is_train, void* state) {
  (void)reqs; (void)is_train; (void)state;
  NDArrayHandle ograd = NULL, igrad = NULL;
  for (int i = 0; i < size; ++i) {
    if (tags[i] == 3 && !ograd) ograd = ptrs[i];
    if (tags[i] == 2 && !igrad) igrad = ptrs[i];
  }
  if (!ograd || !igrad) return 0;
  /* d(in+1)/din = 1: gradient passes through */
  return MXNDArraySyncCopyFromNDArray(igrad, ograd, 0) == 0 ? 1 : 0;
}

/* ---- prop callbacks (enum CustomOpPropCallbacks order) ---- */

static const char* kArgs[] = {"data", NULL};
static const char* kOuts[] = {"output", NULL};
static const char* kAux[] = {NULL};

static int prop_delete(void* state) { (void)state; return 1; }

static int list_arguments(char*** out, void* state) {
  (void)state; *out = (char**)kArgs; return 1;
}
static int list_outputs(char*** out, void* state) {
  (void)state; *out = (char**)kOuts; return 1;
}
static int list_aux(char*** out, void* state) {
  (void)state; *out = (char**)kAux; return 1;
}

static int infer_shape(int num_input, int* ndims, int** shapes,
                       void* state) {
  (void)state;
  if (num_input < 2) return 0;
  ndims[1] = ndims[0];          /* output mirrors the input shape */
  shapes[1] = shapes[0];
  return 1;
}

static int declare_backward_dependency(const int* out_grad,
                                       const int* in_data,
                                       const int* out_data, int* num_dep,
                                       int** rdeps, void* state) {
  (void)in_data; (void)out_data; (void)state;
  static int deps[1];
  deps[0] = out_grad[0];
  *num_dep = 1;
  *rdeps = deps;
  return 1;
}

static int (*g_op_cbs[3])(void);
static void* g_op_ctx[3];

static int create_operator(const char* ctx, int num_inputs,
                           unsigned** shapes, const int* ndims,
                           const int* dtypes, struct MXCallbackList* ret,
                           void* state) {
  (void)ctx; (void)num_inputs; (void)shapes; (void)ndims; (void)dtypes;
  (void)state;
  g_op_cbs[0] = (int (*)(void))op_delete;
  g_op_cbs[1] = (int (*)(void))op_forward;
  g_op_cbs[2] = (int (*)(void))op_backward;
  ret->num_callbacks = 3;
  ret->callbacks = g_op_cbs;
  ret->contexts = g_op_ctx;
  return 1;
}

static int (*g_prop_cbs[7])(void);
static void* g_prop_ctx[7];

int caddone_creator(const char* op_type, const int num_kwargs,
                    const char** keys, const char** values,
                    struct MXCallbackList* ret) {
  (void)op_type; (void)num_kwargs; (void)keys; (void)values;
  g_prop_cbs[0] = (int (*)(void))prop_delete;
  g_prop_cbs[1] = (int (*)(void))list_arguments;
  g_prop_cbs[2] = (int (*)(void))list_outputs;
  g_prop_cbs[3] = (int (*)(void))list_aux;
  g_prop_cbs[4] = (int (*)(void))infer_shape;
  g_prop_cbs[5] = (int (*)(void))declare_backward_dependency;
  g_prop_cbs[6] = (int (*)(void))create_operator;
  ret->num_callbacks = 7;
  ret->callbacks = g_prop_cbs;
  ret->contexts = g_prop_ctx;
  return 1;
}

#ifdef __cplusplus
}
#endif
