"""Data-parallel scaling-efficiency harness (BASELINE.json metric 3).

Measures the fused train step at dp=1/2/4/... over whatever devices
exist, reports throughput, efficiency vs dp=1, and per-step collective
traffic (all-reduce / all-gather / reduce-scatter bytes parsed from the
optimized HLO), and writes a JSON artifact. This is the measuring
instrument for the reference's multi-GPU scaling table
(example/image-classification/README.md:307-319, ~90% efficiency at
8-256 GPUs): on real multi-chip hardware it is one command; on this rig
it validates its plumbing on the virtual 8-device CPU mesh (numbers
there are meaningless, the artifact structure and comm accounting are
not).

Usage:
  python bench_scaling.py                       # resnet50, dp=1..8
  python bench_scaling.py --model mlp --dp 1,2  # tiny smoke (tests)
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python bench_scaling.py --image 64        # virtual-mesh check

The per-chip batch is held constant (weak scaling, like the reference
table), so efficiency = rate(dp) / (dp * rate(1)).

The sharded-update leg (skip with --no-zero-leg) A/Bs the replicated
weight update against the ZeRO dp-sharded one (MXNET_TPU_ZERO,
docs/PARALLEL.md) at the largest measured dp and records per-device
optimizer-state bytes (ideal 1/dp of replicated), per-step collective
traffic, and step time under artifact key ``zero_update``.

The MULTICHIP leg (``--dist``, docs/DISTRIBUTED.md) spawns a REAL
two-process dp=2 pod over the local Gloo launcher and records the
cross-host trainer's step time and per-step collective bytes under
artifact key ``dist`` — the multi-host analog of the rows table (the
same key the ``dist`` CI stage checks; on this rig the numbers price
the Gloo loopback, on a pod they price DCN).
"""
import argparse
import json
import time

import numpy as np

def collective_bytes(hlo_text):
    """Sum output bytes of collective ops in optimized HLO text.

    The accounting now lives in the library
    (mxnet_tpu/observability/hlo.py) so normal training runs can
    record their own comm volume; this compatibility shim delegates
    lazily — the bench drivers keep all mxnet_tpu imports inside
    functions so ``--help`` stays instant."""
    from mxnet_tpu.observability.hlo import collective_bytes as impl
    return impl(hlo_text)


def _build(model, dp, batch_per_chip, image, devices, zero=False):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, parallel
    from mxnet_tpu.gluon import model_zoo, nn

    mesh = parallel.create_mesh({'dp': dp}, devices=devices[:dp])
    if model == 'resnet50':
        net = model_zoo.vision.resnet50_v1()
        classes = 1000
    elif model == 'mlp':
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(64, activation='relu'), nn.Dense(10))
        classes = 10
    else:
        raise ValueError(model)
    net.initialize(mx.init.Xavier())
    on_accel = devices[0].platform != 'cpu'
    if on_accel:
        net.cast('bfloat16')
    net.hybridize(static_alloc=True, static_shape=True)
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    B = dp * batch_per_chip
    shape = (B, 3, image, image) if model == 'resnet50' else (B, 32)
    dtype = 'bfloat16' if on_accel else 'float32'
    x = nd.array(np.random.uniform(-1, 1, shape), dtype=dtype)
    y = nd.array(np.random.randint(0, classes, (B,)))
    pt = parallel.ParallelTrainer(
        net, L, 'sgd', {'learning_rate': 0.05, 'momentum': 0.9}, mesh,
        zero=zero)
    pt.step(x, y)          # compile
    return pt, x, y


def _time_step(pt, x, y, iters, slope):
    def window(n):
        out = pt.step(x, y)
        out.wait_to_read()
        t0 = time.perf_counter()
        for _ in range(n):
            out = pt.step(x, y)
        out.wait_to_read()
        return time.perf_counter() - t0

    if slope:
        # tunneled accelerators: difference out the fixed sync cost
        t_lo = window(iters)
        t_hi = window(3 * iters)
        return (t_hi - t_lo) / (2 * iters)
    return window(iters) / iters


def step_hlo(pt, x, y):
    """Optimized HLO of the compiled fused step (lower() only reads
    shapes — nothing executes, nothing is donated)."""
    import jax.numpy as jnp
    indices = list(range(len(pt._params)))
    hyper = pt._hyper(indices, pt._opt, advance=False)
    key = np.zeros(2, np.uint32)
    xs = tuple(jnp.asarray(a._data) for a in [x])
    ys = tuple(jnp.asarray(a._data) for a in [y])
    lowered = pt._jitted.lower(key, hyper, pt._param_arrays,
                               pt._state_leaves, xs, ys)
    return lowered.compile().as_text()


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--model', default='resnet50',
                   choices=['resnet50', 'mlp'])
    p.add_argument('--dp', default=None,
                   help='comma list of dp sizes (default: 1,2,4,.. up '
                        'to the device count)')
    p.add_argument('--batch-per-chip', type=int, default=None)
    p.add_argument('--image', type=int, default=None)
    p.add_argument('--iters', type=int, default=None)
    p.add_argument('--no-zero-leg', action='store_true',
                   help='skip the sharded-update (ZeRO) A/B leg')
    p.add_argument('--dist', action='store_true',
                   help='add the MULTICHIP leg: a 2-process dp=2 pod '
                        'over the local Gloo launcher (step time + '
                        'collective bytes under artifact key "dist")')
    p.add_argument('--dist-worker', default=None,
                   help=argparse.SUPPRESS)   # internal: pod worker out
    p.add_argument('--out', default='SCALING.json')
    args = p.parse_args(argv)

    if args.dist_worker:
        return _dist_worker(args)

    import os
    import jax
    from mxnet_tpu.resilience import acquire_backend, write_artifact
    if os.environ.get('JAX_PLATFORMS') == 'cpu':
        # the axon PJRT plugin force-prepends the TPU platform and
        # clobbers the env var; pin the config so the virtual-mesh
        # check is hermetic (same workaround as tests/conftest.py)
        jax.config.update('jax_platforms', 'cpu')
    status = acquire_backend()
    if not status.usable:
        # degraded-mode contract (docs/RESILIENCE.md): record the
        # outage in the artifact and exit 0 instead of tracebacking
        print('bench_scaling: backend unavailable after %d attempt(s): '
              '%s' % (status.attempts, status.error), flush=True)
        artifact = {'model': args.model, 'batch_per_chip': None,
                    'image': None, 'weak_scaling': True, 'rows': [],
                    'status': 'unavailable',
                    'backend': status.as_dict(), 'error': status.error}
        write_artifact(args.out, artifact)
        return artifact
    # enumerate the platform acquire_backend settled on: a bare
    # jax.devices() would re-trigger the failed TPU init that the
    # cpu-fallback just absorbed
    devices = jax.devices(status.platform)
    on_accel = devices[0].platform != 'cpu'
    n = len(devices)
    if args.dp:
        dp_list = [int(s) for s in args.dp.split(',')]
    else:
        dp_list = [d for d in (1, 2, 4, 8, 16, 32) if d <= n]
    batch = args.batch_per_chip or (128 if on_accel else 4)
    image = args.image or (224 if on_accel else 32)
    iters = args.iters or (30 if on_accel else 3)

    rows = []
    base_rate = None
    last = None           # (dp, pt, dt, comm, per_kind) of the last row
    for dp in dp_list:
        if dp > n:
            row = {'dp': dp, 'skipped': 'only %d devices' % n}
            rows.append(row)          # artifact stays self-describing
            print(json.dumps(row), flush=True)
            continue
        pt, x, y = _build(args.model, dp, batch, image, devices)
        dt = _time_step(pt, x, y, iters, slope=on_accel)
        rate = dp * batch / dt
        if base_rate is None:
            base_rate = rate / dp   # first measured row is the reference
        comm, per_kind = collective_bytes(step_hlo(pt, x, y))
        row = {
            'dp': dp,
            'global_batch': dp * batch,
            'ms_per_step': round(dt * 1e3, 2),
            'samples_per_sec': round(rate, 1),
            'efficiency_pct': round(100 * rate / (dp * base_rate), 1)
            if base_rate else None,
            'comm_bytes_per_step': comm,
            'comm_by_kind': per_kind,
            'device_kind': devices[0].device_kind,
            'platform': devices[0].platform,
        }
        rows.append(row)
        last = (dp, pt, dt, comm, per_kind)
        print(json.dumps(row), flush=True)

    # sharded-update leg (docs/PARALLEL.md): A/B the replicated weight
    # update against MXNET_TPU_ZERO=1 at the largest measured dp —
    # per-device optimizer-state bytes (the ZeRO memory win, ideal
    # 1/dp), per-step collective traffic (the reduce-scatter +
    # all-gather the sharded update trades the plain all-reduce for),
    # and step time
    zero_leg = None
    measured = [dp for dp in dp_list if dp <= n and dp > 1]
    if not args.no_zero_leg and measured:
        dp = max(measured)

        def leg(zero):
            pt, x, y = _build(args.model, dp, batch, image, devices,
                              zero=zero)
            dt = _time_step(pt, x, y, iters, slope=on_accel)
            per_dev, logical = pt.optimizer_state_bytes()
            comm, per_kind = collective_bytes(step_hlo(pt, x, y))
            return {'ms_per_step': round(dt * 1e3, 2),
                    'opt_state_bytes_per_device': per_dev,
                    'opt_state_bytes_logical': logical,
                    'comm_bytes_per_step': comm,
                    'comm_by_kind': per_kind}

        # free the rows-loop trainer (params + state + executable in
        # device memory) before building anything new — holding two
        # trainers doubles peak HBM at the largest dp; the loop locals
        # alias it too
        reuse = last if last is not None and last[0] == dp else None
        last = pt = x = y = None
        if reuse is not None:
            # the rows loop just compiled+timed this exact replicated
            # config — only the state-bytes accounting is new
            _, pt, dt, comm, per_kind = reuse
            per_dev, logical = pt.optimizer_state_bytes()
            replicated = {'ms_per_step': round(dt * 1e3, 2),
                          'opt_state_bytes_per_device': per_dev,
                          'opt_state_bytes_logical': logical,
                          'comm_bytes_per_step': comm,
                          'comm_by_kind': per_kind}
            del pt, reuse
        else:
            replicated = leg(False)
        sharded = leg(True)
        zero_leg = {
            'dp': dp,
            'replicated': replicated,
            'sharded': sharded,
            'state_bytes_ratio': round(
                sharded['opt_state_bytes_per_device']
                / max(1, replicated['opt_state_bytes_per_device']), 4),
        }
        print(json.dumps({'zero_update': zero_leg}), flush=True)

    dist_leg = None
    if args.dist:
        dist_leg = _dist_leg(batch, iters)
        print(json.dumps({'dist': dist_leg}), flush=True)

    artifact = {'model': args.model, 'batch_per_chip': batch,
                'image': image, 'weak_scaling': True, 'rows': rows,
                'zero_update': zero_leg, 'dist': dist_leg,
                'status': 'ok' if on_accel else 'degraded',
                'backend': status.as_dict(), 'error': status.error}
    write_artifact(args.out, artifact)
    return artifact


def _dist_worker(args):
    """Pod-worker half of the MULTICHIP leg: joined via the launcher
    env, train dp=2 across both processes, rank 0 writes the record."""
    import jax
    jax.config.update('jax_default_matmul_precision', 'float32')
    import mxnet_tpu as mx
    from mxnet_tpu import dist, gluon, nd, parallel
    from mxnet_tpu.gluon import nn

    c = dist.get_coordinator()
    c.start_heartbeat()
    np.random.seed(0)
    mx.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(64, activation='relu'), nn.Dense(10))
    net.initialize(mx.init.Xavier())
    mesh = dist.global_mesh({'dp': 2})
    batch = args.batch_per_chip or 4
    B = 2 * batch
    x = np.random.uniform(-1, 1, (B, 32)).astype('float32')
    y = np.random.randint(0, 10, (B,)).astype('float32')
    lo, hi = dist.host_shard(mesh, B)
    xl, yl = nd.array(x[lo:hi]), nd.array(y[lo:hi])
    pt = parallel.ParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), 'sgd',
        {'learning_rate': 0.05, 'momentum': 0.9}, mesh)
    pt.step(xl, yl)                       # compile
    iters = args.iters or 10
    c.barrier('bench_start', timeout_s=60)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = pt.step(xl, yl)
    out.wait_to_read()
    dt = (time.perf_counter() - t0) / iters
    comm, per_kind = collective_bytes(pt.compiled_text())
    c.barrier('bench_done', timeout_s=60)
    if c.process_id == 0:
        from mxnet_tpu.resilience.checkpoint import atomic_write_bytes
        record = {
            'model': 'mlp',
            'processes': c.process_count,
            'devices_per_host': 1,
            'dp': 2,
            'global_batch': B,
            'ms_per_step': round(dt * 1e3, 2),
            'samples_per_sec': round(B / dt, 1),
            'comm_bytes_per_step': comm,
            'comm_by_kind': per_kind,
            'transport': 'gloo-loopback',
        }
        atomic_write_bytes(args.dist_worker,
                           (json.dumps(record, sort_keys=True)
                            + '\n').encode())
    return 0


def _dist_leg(batch, iters):
    """Spawn the 2-process pod and collect rank 0's record (the
    MULTICHIP bench leg; always the MLP model — the record says so).
    A launch failure degrades to a typed record instead of failing the
    whole bench — same posture as the backend acquire."""
    import os
    import sys
    import tempfile
    from mxnet_tpu.dist import launcher
    with tempfile.TemporaryDirectory() as tmp:
        out = os.path.join(tmp, 'dist_row.json')
        res = launcher.launch_local(
            2,
            [sys.executable, os.path.abspath(__file__),
             '--model', 'mlp', '--batch-per-chip', str(batch),
             '--iters', str(iters), '--dist-worker', out],
            env={'PYTHONPATH': os.pathsep.join(
                [os.path.dirname(os.path.abspath(__file__)),
                 os.environ.get('PYTHONPATH', '')])},
            log_dir=os.path.join(tmp, 'logs'), platform='cpu',
            local_devices=1, timeout=300)
        if not res.ok or not os.path.exists(out):
            # tail the CAUSAL rank's log: a launcher-terminated peer
            # (-15) is collateral, its log hides the real error
            causes = [w for w in res.failures()
                      if w.returncode != -15] or res.failures()
            return {'status': 'failed',
                    'returncodes': res.returncodes,
                    'rank': causes[0].rank if causes else None,
                    'tail': causes[0].log_tail(600) if causes else ''}
        with open(out) as f:
            record = json.load(f)
    record['status'] = 'ok'
    return record


if __name__ == '__main__':
    main()
