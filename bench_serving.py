#!/usr/bin/env python
"""Serving bench: closed-loop latency/throughput sweep over the
bucket ladder, plus the autoregressive generation sweep
(docs/SERVING.md; CI stages 'bench-serving' and 'bench-decode').

Default mode — one-shot inference, for every batch bucket:

  * closed-loop single requests through the micro-batcher (one
    in-flight request per client, ``--clients`` concurrent clients)
    — measures request latency under batching: p50/p99, requests/s;
  * bulk batches of exactly the bucket size through the AOT program
    (``InferenceSession.infer_batch``) — measures the compiled
    program's examples/s ceiling per bucket.

``--decode`` mode — generation, a mixed-length workload (varying
prompt lengths AND generation budgets) decoded two ways over the SAME
frozen decode program:

  * **continuous batching** (the decode engine): sequences join/leave
    the slot register file at token granularity;
  * **flush batching** (the baseline the engine replaces): groups of
    ``slots`` sequences prefill together and the whole group holds
    its slots until the LONGEST member finishes.

Both report tokens/s, time-to-first-token p50/p99 and per-token
latency p50/p99; the payload records the continuous/flush ratios and
a per-request token-stream cross-check (same greedy model, so any
mismatch is an engine bug, not noise).

Writes the standard instrument status JSON (mxnet_tpu.instrument.v2:
``status`` ok|degraded|unavailable, rc 0 on outage — the
BENCH_r05-proof contract every instrument in this repo honors) with
the telemetry summary block.

Usage: python bench_serving.py [--quick] [--decode]
                               [--out BENCH_SERVING.json]
"""
import argparse
import sys
import threading
import time

sys.path.insert(0, '.')
import numpy as np  # noqa: E402

FEATURES = 64
CLASSES = 16


def _build_frozen(max_batch):
    import mxnet_tpu as mx
    from mxnet_tpu import serving
    np.random.seed(5)
    mx.random.seed(5)
    data = mx.sym.Variable('data')
    h = mx.sym.FullyConnected(data, num_hidden=128, name='fc1')
    h = mx.sym.Activation(h, act_type='relu')
    h = mx.sym.FullyConnected(h, num_hidden=128, name='fc2')
    h = mx.sym.Activation(h, act_type='relu')
    h = mx.sym.FullyConnected(h, num_hidden=CLASSES, name='fc3')
    out = mx.sym.SoftmaxOutput(h, name='softmax')
    mod = mx.mod.Module(out, context=mx.context.current_context())
    rs = np.random.RandomState(0)
    x = rs.randn(64, FEATURES).astype('float32')
    y = rs.randint(0, CLASSES, (64,)).astype('float32')
    it = mx.io.NDArrayIter(x, y, batch_size=32)
    mod.fit(it, num_epoch=1, optimizer_params=(('learning_rate', 0.1),))
    return serving.freeze(mod, max_batch=max_batch,
                          name='bench-serving')


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def bench_bucket(session, bucket, seconds, clients):
    """Closed-loop clients + bulk-batch throughput for one bucket."""
    rs = np.random.RandomState(bucket)
    x1 = rs.randn(FEATURES).astype('float32')
    xb = rs.randn(bucket, FEATURES).astype('float32')
    session.infer_batch([xb])          # compile outside the window

    latencies = []
    lock = threading.Lock()
    stop = time.perf_counter() + seconds

    def client():
        mine = []
        while time.perf_counter() < stop:
            t0 = time.perf_counter()
            session.infer(x1, timeout=30)
            mine.append(time.perf_counter() - t0)
        with lock:
            latencies.extend(mine)

    threads = [threading.Thread(target=client)
               for _ in range(clients)]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(seconds + 30)
    wall = time.perf_counter() - t_start

    # bulk path: examples/s of the padded compiled program
    reps = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        session.infer_batch([xb])
        reps += 1
    bulk_dt = time.perf_counter() - t0

    latencies.sort()
    return {
        'bucket': bucket,
        'requests': len(latencies),
        'requests_per_sec': round(len(latencies) / wall, 2)
        if wall else None,
        'latency_p50_ms': round(1e3 * _percentile(latencies, 0.50), 3)
        if latencies else None,
        'latency_p99_ms': round(1e3 * _percentile(latencies, 0.99), 3)
        if latencies else None,
        'bulk_examples_per_sec': round(reps * bucket / bulk_dt, 1)
        if bulk_dt else None,
    }


# ---------------------------------------------------------------------------
# generation sweep (--decode): continuous vs flush batching
# ---------------------------------------------------------------------------

def _decode_workload(quick, slots):
    """Deterministic mixed-length workload: prompts 2..16 tokens,
    generation budgets drawn from a short/long mix — the shape where
    continuous batching earns its keep."""
    rs = np.random.RandomState(17)
    n = 3 * slots if quick else 8 * slots
    budgets = [4, 6, 8, 12, 16, 24]
    return [(list(rs.randint(1, 48, rs.randint(2, 17))),
             int(budgets[rs.randint(len(budgets))]))
            for _ in range(n)]


def _gen_stats(name, wall, ttfts, token_stamps):
    """tokens/s + TTFT/per-token percentiles from per-request
    timestamp traces."""
    tpots = []
    total = 0
    for stamps in token_stamps:
        total += len(stamps)
        tpots.extend(b - a for a, b in zip(stamps, stamps[1:]))
    ttfts = sorted(ttfts)
    tpots.sort()
    ms = lambda v: None if v is None else round(1e3 * v, 3)  # noqa: E731
    return {
        'mode': name,
        'requests': len(ttfts),
        'tokens': total,
        'wall_s': round(wall, 3),
        'tokens_per_sec': round(total / wall, 1) if wall else None,
        'ttft_p50_ms': ms(_percentile(ttfts, 0.50)),
        'ttft_p99_ms': ms(_percentile(ttfts, 0.99)),
        'tpot_p50_ms': ms(_percentile(tpots, 0.50)),
        'tpot_p99_ms': ms(_percentile(tpots, 0.99)),
    }


def _bench_continuous(prog, requests):
    """All requests arrive at t0; the decode engine schedules joins
    and retirements at token granularity."""
    from mxnet_tpu import serving
    session = serving.InferenceSession(prog, watchdog=False,
                                       timeout_s=600.0)
    ttfts = [None] * len(requests)
    stamps = [None] * len(requests)
    tokens = [None] * len(requests)

    def consume(i, stream, t0):
        mine = []
        for _tok in stream:
            mine.append(time.perf_counter())
        ttfts[i] = mine[0] - t0 if mine else float('inf')
        stamps[i] = mine
        tokens[i] = list(stream.tokens)

    try:
        t0 = time.perf_counter()
        streams = [session.generate(p, max_new_tokens=n)
                   for p, n in requests]
        threads = [threading.Thread(target=consume, args=(i, s, t0))
                   for i, s in enumerate(streams)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(600)
        wall = time.perf_counter() - t0
    finally:
        session.close()
    return _gen_stats('continuous', wall, ttfts, stamps), tokens


def _bench_flush(prog, requests):
    """Baseline: groups of ``slots`` prefill together and decode until
    the whole group finishes — finished members' rows are wasted and
    the next group waits (exactly what continuous batching removes)."""
    slots = prog.slots
    ttfts = [None] * len(requests)
    stamps = [[] for _ in requests]
    tokens = [None] * len(requests)
    cache = prog.new_cache()
    t0 = time.perf_counter()
    for base in range(0, len(requests), slots):
        group = requests[base:base + slots]
        states = []
        for i, (prompt, max_new) in enumerate(group):
            cache, tok, _ = prog.run_prefill(cache, prompt, i)
            now = time.perf_counter()
            ttfts[base + i] = now - t0
            stamps[base + i].append(now)
            states.append({'toks': [tok], 'pos': len(prompt),
                           'last': tok, 'max_new': max_new})
        while True:
            live = [i for i, s in enumerate(states)
                    if len(s['toks']) < s['max_new']
                    and s['pos'] + 1 < prog.max_len]
            if not live:
                break
            tk = np.zeros(slots, 'int32')
            ps = np.zeros(slots, 'int32')
            for i, s in enumerate(states):
                tk[i] = s['last']
                ps[i] = s['pos']
            cache, out, _ = prog.run_step(cache, tk, ps)
            now = time.perf_counter()
            for i in live:
                s = states[i]
                s['pos'] += 1
                s['last'] = int(out[i])
                s['toks'].append(s['last'])
                stamps[base + i].append(now)
        for i, s in enumerate(states):
            tokens[base + i] = s['toks']
    wall = time.perf_counter() - t0
    return _gen_stats('flush', wall, ttfts, stamps), tokens


def run_decode(status, args):
    from mxnet_tpu.serving.decode import DecodeProgram, init_rnn_lm

    slots = 4 if args.quick else 8
    model, params = init_rnn_lm(vocab=48, embed=32, hidden=64,
                                layers=1, mode='lstm', max_len=64,
                                seed=9)
    prog = DecodeProgram(model, params, slots=slots,
                         prefill_buckets=(4, 8, 16))
    prog.warmup()          # compile outside the timed windows
    requests = _decode_workload(args.quick, slots)

    flush_rec, flush_tokens = _bench_flush(prog, requests)
    cont_rec, cont_tokens = _bench_continuous(prog, requests)
    mismatches = sum(1 for a, b in zip(cont_tokens, flush_tokens)
                     if a != b)
    for rec in (flush_rec, cont_rec):
        print('%-11s %7s tok/s  ttft p50/p99 %s/%s ms  '
              'tpot p50/p99 %s/%s ms'
              % (rec['mode'], rec['tokens_per_sec'],
                 rec['ttft_p50_ms'], rec['ttft_p99_ms'],
                 rec['tpot_p50_ms'], rec['tpot_p99_ms']), flush=True)

    bound = len(prog.prefill_buckets) + 1
    speedup = (cont_rec['tokens_per_sec']
               / flush_rec['tokens_per_sec']) \
        if flush_rec['tokens_per_sec'] else None
    payload = {
        'metrics': [{
            'metric': 'decode_generation_sweep',
            'unit': 'tokens/s',
            'slots': slots,
            'requests': len(requests),
            'prefill_buckets': list(prog.prefill_buckets),
            'continuous': cont_rec,
            'flush': flush_rec,
            'tokens_per_sec_ratio': round(speedup, 3)
            if speedup else None,
            'continuous_beats_flush': bool(
                speedup and speedup > 1.0
                and cont_rec['ttft_p99_ms'] < flush_rec['ttft_p99_ms']),
            'token_stream_mismatches': mismatches,
            'recompile_count': prog.compile_count,
            'recompile_bound': bound,
            'recompiles_bounded': prog.compile_count <= bound,
        }],
    }
    try:
        from mxnet_tpu import observability
        payload['telemetry'] = observability.summary()
    except Exception as e:
        payload['telemetry'] = {'enabled': False,
                                'error': '%s: %s'
                                % (type(e).__name__, e)}
    m = payload['metrics'][0]
    if not m['recompiles_bounded']:
        raise AssertionError(
            '%d decode programs compiled; bound is prefill ladder + 1'
            ' = %d' % (prog.compile_count, bound))
    if mismatches:
        raise AssertionError(
            '%d/%d token streams differ between continuous and flush '
            'decoding (same greedy model: engine bug)'
            % (mismatches, len(requests)))
    return payload


def run(status, args):
    from mxnet_tpu import serving

    max_batch = 8 if args.quick else 32
    frozen = _build_frozen(max_batch)
    frozen.warmup()        # compile the ladder outside the timed windows
    session = serving.InferenceSession(
        frozen, deadline_ms=args.deadline_ms, watchdog=False)
    seconds = 0.5 if args.quick else 3.0
    sweep = []
    try:
        for bucket in frozen.policy.buckets:
            rec = bench_bucket(session, bucket, seconds, args.clients)
            print('bucket %3d: %s req/s, p50 %s ms, p99 %s ms, bulk '
                  '%s ex/s' % (bucket, rec['requests_per_sec'],
                               rec['latency_p50_ms'],
                               rec['latency_p99_ms'],
                               rec['bulk_examples_per_sec']),
                  flush=True)
            sweep.append(rec)
    finally:
        session.close()

    recompiles = frozen.compile_count
    payload = {
        'metrics': [{
            'metric': 'serving_bucket_sweep',
            'unit': 'requests/s',
            'clients': args.clients,
            'deadline_ms': args.deadline_ms,
            'buckets': list(frozen.policy.buckets),
            'sweep': sweep,
            'recompile_count': recompiles,
            'recompile_bound': len(frozen.policy.buckets),
            'recompiles_bounded': recompiles
            <= len(frozen.policy.buckets),
        }],
    }
    try:
        from mxnet_tpu import observability
        payload['telemetry'] = observability.summary()
    except Exception as e:    # telemetry must never sink the artifact
        payload['telemetry'] = {'enabled': False,
                                'error': '%s: %s'
                                % (type(e).__name__, e)}
    if not payload['metrics'][0]['recompiles_bounded']:
        raise AssertionError(
            '%d programs compiled for a %d-bucket ladder'
            % (recompiles, len(frozen.policy.buckets)))
    return payload


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--out', default='BENCH_SERVING.json')
    p.add_argument('--quick', action='store_true',
                   help='CI-sized sweep (small ladder, short windows)')
    p.add_argument('--decode', action='store_true',
                   help='generation sweep: continuous vs flush '
                        'batching (tokens/s, TTFT, per-token latency)')
    p.add_argument('--clients', type=int, default=4)
    p.add_argument('--deadline-ms', type=float, default=2.0)
    args = p.parse_args()

    from mxnet_tpu.resilience import run_instrument
    fn = run_decode if args.decode else run
    return run_instrument('bench_decode' if args.decode
                          else 'bench_serving',
                          lambda status: fn(status, args),
                          out=args.out)


if __name__ == '__main__':
    sys.exit(main())
