#!/usr/bin/env python
"""Serving bench: closed-loop latency/throughput sweep over the
bucket ladder, plus the autoregressive generation sweep
(docs/SERVING.md; CI stages 'bench-serving' and 'bench-decode').

Default mode — one-shot inference, for every batch bucket:

  * closed-loop single requests through the micro-batcher (one
    in-flight request per client, ``--clients`` concurrent clients)
    — measures request latency under batching: p50/p99, requests/s;
  * bulk batches of exactly the bucket size through the AOT program
    (``InferenceSession.infer_batch``) — measures the compiled
    program's examples/s ceiling per bucket.

``--decode`` mode — generation, a mixed-length workload (varying
prompt lengths AND generation budgets) decoded two ways over the SAME
frozen decode program:

  * **continuous batching** (the decode engine): sequences join/leave
    the slot register file at token granularity;
  * **flush batching** (the baseline the engine replaces): groups of
    ``slots`` sequences prefill together and the whole group holds
    its slots until the LONGEST member finishes.

Both report tokens/s, time-to-first-token p50/p99 and per-token
latency p50/p99; the payload records the continuous/flush ratios and
a per-request token-stream cross-check (same greedy model, so any
mismatch is an engine bug, not noise).

Writes the standard instrument status JSON (mxnet_tpu.instrument.v2:
``status`` ok|degraded|unavailable, rc 0 on outage — the
BENCH_r05-proof contract every instrument in this repo honors) with
the telemetry summary block.

Usage: python bench_serving.py [--quick] [--decode]
                               [--out BENCH_SERVING.json]
"""
import argparse
import os
import sys
import threading
import time

sys.path.insert(0, '.')
import numpy as np  # noqa: E402

FEATURES = 64
CLASSES = 16


def _build_frozen(max_batch):
    import mxnet_tpu as mx
    from mxnet_tpu import serving
    np.random.seed(5)
    mx.random.seed(5)
    data = mx.sym.Variable('data')
    h = mx.sym.FullyConnected(data, num_hidden=128, name='fc1')
    h = mx.sym.Activation(h, act_type='relu')
    h = mx.sym.FullyConnected(h, num_hidden=128, name='fc2')
    h = mx.sym.Activation(h, act_type='relu')
    h = mx.sym.FullyConnected(h, num_hidden=CLASSES, name='fc3')
    out = mx.sym.SoftmaxOutput(h, name='softmax')
    mod = mx.mod.Module(out, context=mx.context.current_context())
    rs = np.random.RandomState(0)
    x = rs.randn(64, FEATURES).astype('float32')
    y = rs.randint(0, CLASSES, (64,)).astype('float32')
    it = mx.io.NDArrayIter(x, y, batch_size=32)
    mod.fit(it, num_epoch=1, optimizer_params=(('learning_rate', 0.1),))
    return serving.freeze(mod, max_batch=max_batch,
                          name='bench-serving')


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def bench_bucket(session, bucket, seconds, clients):
    """Closed-loop clients + bulk-batch throughput for one bucket."""
    rs = np.random.RandomState(bucket)
    x1 = rs.randn(FEATURES).astype('float32')
    xb = rs.randn(bucket, FEATURES).astype('float32')
    session.infer_batch([xb])          # compile outside the window

    latencies = []
    lock = threading.Lock()
    stop = time.perf_counter() + seconds

    def client():
        mine = []
        while time.perf_counter() < stop:
            t0 = time.perf_counter()
            session.infer(x1, timeout=30)
            mine.append(time.perf_counter() - t0)
        with lock:
            latencies.extend(mine)

    threads = [threading.Thread(target=client)
               for _ in range(clients)]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(seconds + 30)
    wall = time.perf_counter() - t_start

    # bulk path: examples/s of the padded compiled program
    reps = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        session.infer_batch([xb])
        reps += 1
    bulk_dt = time.perf_counter() - t0

    latencies.sort()
    return {
        'bucket': bucket,
        'requests': len(latencies),
        'requests_per_sec': round(len(latencies) / wall, 2)
        if wall else None,
        'latency_p50_ms': round(1e3 * _percentile(latencies, 0.50), 3)
        if latencies else None,
        'latency_p99_ms': round(1e3 * _percentile(latencies, 0.99), 3)
        if latencies else None,
        'bulk_examples_per_sec': round(reps * bucket / bulk_dt, 1)
        if bulk_dt else None,
    }


# ---------------------------------------------------------------------------
# generation sweep (--decode): continuous vs flush batching
# ---------------------------------------------------------------------------

def _decode_workload(quick, slots):
    """Deterministic mixed-length workload: prompts 2..16 tokens,
    generation budgets drawn from a short/long mix — the shape where
    continuous batching earns its keep."""
    rs = np.random.RandomState(17)
    n = 3 * slots if quick else 8 * slots
    budgets = [4, 6, 8, 12, 16, 24]
    return [(list(rs.randint(1, 48, rs.randint(2, 17))),
             int(budgets[rs.randint(len(budgets))]))
            for _ in range(n)]


def _gen_stats(name, wall, ttfts, token_stamps):
    """tokens/s + TTFT/per-token percentiles from per-request
    timestamp traces."""
    tpots = []
    total = 0
    for stamps in token_stamps:
        total += len(stamps)
        tpots.extend(b - a for a, b in zip(stamps, stamps[1:]))
    ttfts = sorted(ttfts)
    tpots.sort()
    ms = lambda v: None if v is None else round(1e3 * v, 3)  # noqa: E731
    return {
        'mode': name,
        'requests': len(ttfts),
        'tokens': total,
        'wall_s': round(wall, 3),
        'tokens_per_sec': round(total / wall, 1) if wall else None,
        'ttft_p50_ms': ms(_percentile(ttfts, 0.50)),
        'ttft_p99_ms': ms(_percentile(ttfts, 0.99)),
        'tpot_p50_ms': ms(_percentile(tpots, 0.50)),
        'tpot_p99_ms': ms(_percentile(tpots, 0.99)),
    }


def _bench_continuous(prog, requests):
    """All requests arrive at t0; the decode engine schedules joins
    and retirements at token granularity."""
    from mxnet_tpu import serving
    session = serving.InferenceSession(prog, watchdog=False,
                                       timeout_s=600.0)
    ttfts = [None] * len(requests)
    stamps = [None] * len(requests)
    tokens = [None] * len(requests)

    def consume(i, stream, t0):
        mine = []
        for _tok in stream:
            mine.append(time.perf_counter())
        ttfts[i] = mine[0] - t0 if mine else float('inf')
        stamps[i] = mine
        tokens[i] = list(stream.tokens)

    try:
        t0 = time.perf_counter()
        streams = [session.generate(p, max_new_tokens=n)
                   for p, n in requests]
        threads = [threading.Thread(target=consume, args=(i, s, t0))
                   for i, s in enumerate(streams)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(600)
        wall = time.perf_counter() - t0
    finally:
        session.close()
    return _gen_stats('continuous', wall, ttfts, stamps), tokens


def _bench_flush(prog, requests):
    """Baseline: groups of ``slots`` prefill together and decode until
    the whole group finishes — finished members' rows are wasted and
    the next group waits (exactly what continuous batching removes)."""
    slots = prog.slots
    ttfts = [None] * len(requests)
    stamps = [[] for _ in requests]
    tokens = [None] * len(requests)
    cache = prog.new_cache()
    t0 = time.perf_counter()
    for base in range(0, len(requests), slots):
        group = requests[base:base + slots]
        states = []
        for i, (prompt, max_new) in enumerate(group):
            cache, tok, _ = prog.run_prefill(cache, prompt, i)
            now = time.perf_counter()
            ttfts[base + i] = now - t0
            stamps[base + i].append(now)
            states.append({'toks': [tok], 'pos': len(prompt),
                           'last': tok, 'max_new': max_new})
        while True:
            live = [i for i, s in enumerate(states)
                    if len(s['toks']) < s['max_new']
                    and s['pos'] + 1 < prog.max_len]
            if not live:
                break
            tk = np.zeros(slots, 'int32')
            ps = np.zeros(slots, 'int32')
            for i, s in enumerate(states):
                tk[i] = s['last']
                ps[i] = s['pos']
            cache, out, _ = prog.run_step(cache, tk, ps)
            now = time.perf_counter()
            for i in live:
                s = states[i]
                s['pos'] += 1
                s['last'] = int(out[i])
                s['toks'].append(s['last'])
                stamps[base + i].append(now)
        for i, s in enumerate(states):
            tokens[base + i] = s['toks']
    wall = time.perf_counter() - t0
    return _gen_stats('flush', wall, ttfts, stamps), tokens


def run_decode(status, args):
    from mxnet_tpu.serving.decode import DecodeProgram, init_rnn_lm

    slots = 4 if args.quick else 8
    model, params = init_rnn_lm(vocab=48, embed=32, hidden=64,
                                layers=1, mode='lstm', max_len=64,
                                seed=9)
    prog = DecodeProgram(model, params, slots=slots,
                         prefill_buckets=(4, 8, 16))
    prog.warmup()          # compile outside the timed windows
    requests = _decode_workload(args.quick, slots)

    flush_rec, flush_tokens = _bench_flush(prog, requests)
    cont_rec, cont_tokens = _bench_continuous(prog, requests)
    mismatches = sum(1 for a, b in zip(cont_tokens, flush_tokens)
                     if a != b)
    for rec in (flush_rec, cont_rec):
        print('%-11s %7s tok/s  ttft p50/p99 %s/%s ms  '
              'tpot p50/p99 %s/%s ms'
              % (rec['mode'], rec['tokens_per_sec'],
                 rec['ttft_p50_ms'], rec['ttft_p99_ms'],
                 rec['tpot_p50_ms'], rec['tpot_p99_ms']), flush=True)

    bound = len(prog.prefill_buckets) + 1
    speedup = (cont_rec['tokens_per_sec']
               / flush_rec['tokens_per_sec']) \
        if flush_rec['tokens_per_sec'] else None
    payload = {
        'metrics': [{
            'metric': 'decode_generation_sweep',
            'unit': 'tokens/s',
            'slots': slots,
            'requests': len(requests),
            'prefill_buckets': list(prog.prefill_buckets),
            'continuous': cont_rec,
            'flush': flush_rec,
            'tokens_per_sec_ratio': round(speedup, 3)
            if speedup else None,
            'continuous_beats_flush': bool(
                speedup and speedup > 1.0
                and cont_rec['ttft_p99_ms'] < flush_rec['ttft_p99_ms']),
            'token_stream_mismatches': mismatches,
            'recompile_count': prog.compile_count,
            'recompile_bound': bound,
            'recompiles_bounded': prog.compile_count <= bound,
        }],
    }
    try:
        from mxnet_tpu import observability
        payload['telemetry'] = observability.summary()
    except Exception as e:
        payload['telemetry'] = {'enabled': False,
                                'error': '%s: %s'
                                % (type(e).__name__, e)}
    m = payload['metrics'][0]
    if not m['recompiles_bounded']:
        raise AssertionError(
            '%d decode programs compiled; bound is prefill ladder + 1'
            ' = %d' % (prog.compile_count, bound))
    if mismatches:
        raise AssertionError(
            '%d/%d token streams differ between continuous and flush '
            'decoding (same greedy model: engine bug)'
            % (mismatches, len(requests)))
    return payload


# ---------------------------------------------------------------------------
# paged KV cache sweep (--paged): capacity at equal HBM budget,
# prefix-sharing TTFT, speculative decoding A/B
# ---------------------------------------------------------------------------

def _paged_model(quick):
    from mxnet_tpu.serving.decode import init_transformer_lm
    if quick:
        return init_transformer_lm(vocab=48, units=32, hidden=48,
                                   layers=2, heads=4, max_len=96,
                                   seed=11)
    return init_transformer_lm(vocab=96, units=64, hidden=128,
                               layers=4, heads=8, max_len=256,
                               seed=11)


def _greedy_reference(model, params, prompt, n):
    import jax.numpy as jnp
    dev = {k: jnp.asarray(v) for k, v in params.items()}
    toks = list(prompt)
    out = []
    for _ in range(n):
        full = np.asarray(model.full_forward(
            dev, jnp.asarray([toks], 'int32')))
        t = int(full[0, -1].argmax())
        out.append(t)
        toks.append(t)
    return out


def _capacity_leg(model, params, quick):
    """Max concurrent sequences at EQUAL HBM budget, slot vs paged —
    measured via the pool-bytes accounting and confirmed by actually
    admitting that many sequences into a live engine."""
    from mxnet_tpu.serving.decode import (DecodeEngine, DecodeProgram,
                                          PagedDecodeProgram)
    slot_slots = 4 if quick else 8
    page_size = 8 if quick else 16
    slot_prog = DecodeProgram(model, params, slots=slot_slots,
                              prefill_buckets=(8,))
    budget = slot_prog.cache_bytes()          # the HBM budget to match
    # workload: prompt 8 + up to 6 generated -> <= 14-token sequences
    prompt_len, gen = 8, 6
    paged_tmp = PagedDecodeProgram(model, params, slots=1,
                                   prefill_buckets=(8,),
                                   page_size=page_size)
    pages_budget = budget // paged_tmp.page_bytes()
    per_seq_pages = -(-(prompt_len + gen) // page_size)
    capacity = int(pages_budget // per_seq_pages)
    prog = PagedDecodeProgram(model, params, slots=capacity,
                              prefill_buckets=(8,),
                              page_size=page_size,
                              pages=pages_budget + 1)
    prog.warmup()
    eng = DecodeEngine(prog, timeout_s=120.0, max_queue=capacity + 4)
    rs = np.random.RandomState(23)
    try:
        streams = [eng.generate(list(rs.randint(1, 40, prompt_len)),
                                max_new_tokens=gen)
                   for _ in range(capacity)]
        peak = 0
        deadline = time.perf_counter() + 120
        while time.perf_counter() < deadline:
            st = eng.stats()
            peak = max(peak, st['active'])
            if all(s.done() for s in streams):
                break
            time.sleep(0.005)
        st = eng.stats()
        for s in streams:
            s.result(60)
    finally:
        eng.close()
    return {
        'hbm_budget_bytes': int(budget),
        'page_size': page_size,
        'slot': {'max_concurrent_sequences': slot_slots,
                 'per_sequence_bytes':
                     int(slot_prog.per_sequence_bytes())},
        'paged': {'max_concurrent_sequences': capacity,
                  'per_sequence_bytes': int(per_seq_pages
                                            * prog.page_bytes()),
                  'pool_bytes': int(prog.cache_bytes()),
                  'peak_active_measured': peak,
                  'pool_exhausted': st['counts']['pool_exhausted']},
        'concurrency_ratio': round(capacity / float(slot_slots), 3),
        'all_completed': True,
    }


def _ttft_run(model, params, requests, prefix_cache, page_size,
              max_len_bucket):
    """Drive one engine over the shared-prefix workload; returns
    sorted TTFTs + engine stats."""
    import threading as _threading
    from mxnet_tpu.serving.decode import (DecodeEngine,
                                          PagedDecodeProgram)
    prog = PagedDecodeProgram(model, params, slots=4,
                              prefill_buckets=(max_len_bucket,),
                              page_size=page_size)
    prog.warmup()
    eng = DecodeEngine(prog, timeout_s=300.0,
                       max_queue=len(requests) + 4,
                       prefix_cache=prefix_cache)
    # execute (not just compile) every program once outside the timed
    # window — a compiled executable's FIRST run carries one-time
    # setup cost that would otherwise land on whichever leg runs
    # fewer prefills
    eng.generate([43, 42, 41], max_new_tokens=2).result(120)
    ttfts = [None] * len(requests)

    def consume(i, stream, t0):
        # the iterator re-raises a failed stream's typed error; the
        # finally keeps ttfts[i] a float either way so the percentile
        # math reports the failure as inf instead of dying on None
        try:
            for _tok in stream:
                if ttfts[i] is None:
                    ttfts[i] = time.perf_counter() - t0
        except Exception:
            pass
        finally:
            if ttfts[i] is None:
                ttfts[i] = float('inf')

    try:
        t0 = time.perf_counter()
        streams = [eng.generate(p, max_new_tokens=n)
                   for p, n in requests]
        threads = [_threading.Thread(target=consume, args=(i, s, t0))
                   for i, s in enumerate(streams)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        wall = time.perf_counter() - t0
        st = eng.stats()
    finally:
        eng.close()
    return sorted(ttfts), wall, st


def _prefix_leg(model, params, quick):
    """Shared-prefix workload (a few hot system prompts + short user
    suffixes): TTFT with prefix sharing vs without, same rig, same
    program geometry."""
    rs = np.random.RandomState(31)
    n_req = 20 if quick else 48
    sys_len = 56 if quick else 120
    bucket = 64 if quick else 128
    page_size = 8 if quick else 16
    # Zipf-distributed choice over 3 system prompts (rank-skewed: the
    # hot prompt dominates, the tail still occurs). Page-aligned
    # system prompts + one-token user suffixes + short generations
    # keep the workload prefill-dominated — the regime prefix sharing
    # targets: every no-sharing admit re-runs the whole bucket-sized
    # prefill (~6x a decode step on this rig), a hit replaces it with
    # ONE decode step riding the already-batched tick
    sys_prompts = [list(rs.randint(1, 40, sys_len)) for _ in range(3)]
    weights = np.array([1.0 / (r + 1) for r in range(3)])
    weights /= weights.sum()
    requests = []
    for _ in range(n_req):
        sp = sys_prompts[rs.choice(3, p=weights)]
        requests.append((sp + [int(rs.randint(1, 40))], 3))
    shared, wall_s, st_s = _ttft_run(model, params, requests, True,
                                     page_size, bucket)
    unshared, wall_u, st_u = _ttft_run(model, params, requests, False,
                                       page_size, bucket)
    ms = lambda v: None if v is None else round(1e3 * v, 3)  # noqa: E731
    return {
        'requests': n_req, 'system_prompt_len': sys_len,
        'zipf_system_prompts': len(sys_prompts),
        'sharing': {
            'ttft_p50_ms': ms(_percentile(shared, 0.50)),
            'ttft_p99_ms': ms(_percentile(shared, 0.99)),
            'wall_s': round(wall_s, 3),
            'prefix_hits': st_s['counts']['prefix_hits'],
            'prefix_tokens_saved':
                st_s['counts']['prefix_tokens_saved'],
            'cow_copies': st_s['counts']['cow_copies'],
        },
        'no_sharing': {
            'ttft_p50_ms': ms(_percentile(unshared, 0.50)),
            'ttft_p99_ms': ms(_percentile(unshared, 0.99)),
            'wall_s': round(wall_u, 3),
        },
        'ttft_p99_improved': (_percentile(shared, 0.99)
                              < _percentile(unshared, 0.99)),
    }


def _spec_leg(model, params, quick):
    """Speculative decoding A/B: tokens/s and acceptance rate with a
    small draft vs the plain paged engine, platform-tagged (CPU-rig
    numbers are honest: a toy draft costs a comparable step to the
    toy target, so the win only materializes at real model ratios)."""
    import jax
    from mxnet_tpu.serving.decode import (DecodeEngine, DecodeProgram,
                                          PagedDecodeProgram,
                                          init_transformer_lm)
    slots = 4
    page_size = 8 if quick else 16
    spec_k = 3
    vocab = int(model.vocab)
    dmodel, dparams = init_transformer_lm(
        vocab, units=16, hidden=16, layers=1, heads=2,
        max_len=model.max_len, seed=7)
    rs = np.random.RandomState(41)
    requests = [(list(rs.randint(1, vocab - 4, 6)), 10 if quick
                 else 24) for _ in range(2 * slots)]

    def drive(spec):
        prog = PagedDecodeProgram(model, params, slots=slots,
                                  prefill_buckets=(8,),
                                  page_size=page_size,
                                  spec_k=spec_k if spec else 0)
        prog.warmup()
        draft = None
        if spec:
            draft = DecodeProgram(dmodel, dparams, slots=slots,
                                  prefill_buckets=(8,))
            draft.warmup()
        eng = DecodeEngine(prog, timeout_s=300.0,
                           max_queue=len(requests) + 4, draft=draft)
        try:
            t0 = time.perf_counter()
            streams = [eng.generate(p, max_new_tokens=n)
                       for p, n in requests]
            outs = [s.result(300) for s in streams]
            wall = time.perf_counter() - t0
            st = eng.stats()
        finally:
            eng.close()
        tokens = sum(len(o) for o in outs)
        return {'tokens': tokens, 'wall_s': round(wall, 3),
                'tokens_per_sec': round(tokens / wall, 1)
                if wall else None}, st, outs

    plain_rec, _plain_st, plain_outs = drive(spec=False)
    spec_rec, spec_st, _spec_outs = drive(spec=True)
    return {
        'platform': jax.default_backend(),
        'spec_k': spec_k,
        'draft': 'transformer_lm-1layer-16u',
        'baseline': plain_rec,
        'speculative': dict(spec_rec,
                            acceptance_rate=spec_st['spec']
                            ['acceptance_rate'],
                            proposed=spec_st['spec']['proposed'],
                            accepted=spec_st['spec']['accepted']),
        'tokens_per_sec_ratio': round(
            spec_rec['tokens_per_sec'] / plain_rec['tokens_per_sec'],
            3) if plain_rec['tokens_per_sec'] else None,
    }, plain_outs, requests


def run_paged(status, args):
    """--paged: the decode-memory-wall sweep (docs/SERVING.md "Paged
    KV cache, prefix sharing, speculative decoding")."""
    model, params = _paged_model(args.quick)

    capacity = _capacity_leg(model, params, args.quick)
    print('capacity @ equal HBM: slot %d -> paged %d concurrent '
          '(%.1fx), pool_exhausted=%d'
          % (capacity['slot']['max_concurrent_sequences'],
             capacity['paged']['max_concurrent_sequences'],
             capacity['concurrency_ratio'],
             capacity['paged']['pool_exhausted']), flush=True)

    prefix = _prefix_leg(model, params, args.quick)
    print('prefix TTFT p99: sharing %s ms vs no-sharing %s ms '
          '(hits=%d, saved=%d tokens)'
          % (prefix['sharing']['ttft_p99_ms'],
             prefix['no_sharing']['ttft_p99_ms'],
             prefix['sharing']['prefix_hits'],
             prefix['sharing']['prefix_tokens_saved']), flush=True)

    spec, plain_outs, spec_requests = _spec_leg(model, params,
                                               args.quick)
    print('speculative: %s tok/s vs baseline %s tok/s, acceptance %s'
          % (spec['speculative']['tokens_per_sec'],
             spec['baseline']['tokens_per_sec'],
             spec['speculative']['acceptance_rate']), flush=True)

    # bit-identity proof: the non-speculative paged streams equal the
    # uncached whole-sequence reference
    mismatches = 0
    for (prompt, n), out in zip(spec_requests[:4], plain_outs[:4]):
        if out != _greedy_reference(model, params, prompt, len(out)):
            mismatches += 1
    payload = {
        'metrics': [{
            'metric': 'paged_decode_sweep',
            'unit': 'concurrent sequences / tokens/s',
            'capacity_equal_hbm': capacity,
            'prefix_sharing': prefix,
            'speculative': spec,
            'paged_bit_identity_mismatches': mismatches,
        }],
    }
    try:
        from mxnet_tpu import observability
        payload['telemetry'] = observability.summary()
    except Exception as e:
        payload['telemetry'] = {'enabled': False,
                                'error': '%s: %s'
                                % (type(e).__name__, e)}
    if mismatches:
        raise AssertionError(
            '%d non-speculative paged token streams differ from the '
            'uncached reference' % mismatches)
    if capacity['concurrency_ratio'] < 4.0:
        raise AssertionError(
            'paged capacity at equal HBM budget is %.2fx the slot '
            'cache; the acceptance bar is >= 4x'
            % capacity['concurrency_ratio'])
    if capacity['paged']['pool_exhausted']:
        raise AssertionError('accounting-derived capacity exhausted '
                             'the pool — pool-bytes accounting is '
                             'wrong')
    share_p99 = prefix['sharing']['ttft_p99_ms']
    noshare_p99 = prefix['no_sharing']['ttft_p99_ms']
    if share_p99 is not None and noshare_p99 is not None \
            and share_p99 > noshare_p99 * 1.1:
        raise AssertionError(
            'prefix sharing worsened TTFT p99 (%.1f ms vs %.1f ms '
            'no-sharing, >10%% past noise) on the prefix-heavy '
            'workload' % (share_p99, noshare_p99))
    return payload


# ---------------------------------------------------------------------------
# multi-adapter sweep (--adapters): Zipf fleet rotation at zero
# retraces, adapter-vs-base throughput A/B
# ---------------------------------------------------------------------------

def run_adapters(status, args):
    """--adapters: the multi-adapter serving sweep (docs/SERVING.md
    "Multi-adapter serving & sampling"). One paged program frozen
    with an adapter pool in its compiled signature serves a Zipf
    rotation over 8 LoRA artifacts with half the traffic sampled;
    gates zero retraces after warmup, the whole fleet resident, and
    reports the adapter-traffic throughput next to a base-only run
    of the same program (the overhead of gathering per-slot deltas
    inside the one compiled step)."""
    import tempfile
    import jax
    from mxnet_tpu.serving.adapters import (AdapterSpec, init_adapter,
                                            save_adapter)
    from mxnet_tpu.serving.decode import (DecodeEngine,
                                          PagedDecodeProgram)
    model, params = _paged_model(args.quick)
    fleet, rank, slots = 8, 4, 4
    page_size = 8 if args.quick else 16
    aspec = AdapterSpec.for_model(model, rank=rank,
                                  capacity=fleet + 1)
    prog = PagedDecodeProgram(model, params, slots=slots,
                              prefill_buckets=(8,),
                              page_size=page_size,
                              adapter_spec=aspec)
    vocab = int(model.vocab)
    rs = np.random.RandomState(17)
    requests = [(list(rs.randint(1, vocab - 4, 6)),
                 10 if args.quick else 24)
                for _ in range(4 * slots)]

    def drive(eng, use_fleet):
        t0 = time.perf_counter()
        streams = []
        for i, (prompt, n) in enumerate(requests):
            kw = {}
            if use_fleet:
                # harmonic Zipf over base + fleet, sampled every
                # other request — the loadgen adapters-mode shape
                kw['adapter'] = 'ad%d' % (i % fleet) if i % 3 else \
                    'base'
                if i % 2:
                    kw.update(temperature=0.8, top_p=0.9, seed=i)
            streams.append(eng.generate(prompt, max_new_tokens=n,
                                        **kw))
        outs = [s.result(300) for s in streams]
        wall = time.perf_counter() - t0
        tokens = sum(len(o) for o in outs)
        return {'tokens': tokens, 'wall_s': round(wall, 3),
                'tokens_per_sec': round(tokens / wall, 1)
                if wall else None}

    with tempfile.TemporaryDirectory() as root:
        for i in range(fleet):
            save_adapter(os.path.join(root, 'ad%d' % i),
                         init_adapter(model, rank=rank, seed=60 + i,
                                      scale=50.0, name='ad%d' % i))
        eng = DecodeEngine(prog, timeout_s=300.0,
                           max_queue=len(requests) + 4,
                           adapters=root)
        try:
            # warmup every compiled path (greedy/sampled x
            # base/adapter) and pre-load the fleet, then snapshot
            for kw in ({}, {'temperature': 0.8, 'seed': 1},
                       *({'adapter': 'ad%d' % i} for i in
                         range(fleet)),
                       {'adapter': 'ad0', 'temperature': 0.5,
                        'seed': 2}):
                eng.generate([1, 2, 3], max_new_tokens=4,
                             **kw).result(300)
            tc0 = dict(prog.trace_counts)
            base_rec = drive(eng, use_fleet=False)
            fleet_rec = drive(eng, use_fleet=True)
            retraced = {k: v for k, v in prog.trace_counts.items()
                        if tc0.get(k) != v}
            st = eng.stats()
        finally:
            eng.close()
    print('adapters: fleet %s tok/s vs base-only %s tok/s, '
          'resident=%d loads=%d, retraced=%s'
          % (fleet_rec['tokens_per_sec'], base_rec['tokens_per_sec'],
             st['adapters']['resident'], st['adapters']['loads'],
             retraced or 'none'), flush=True)
    payload = {
        'metrics': [{
            'metric': 'multi_adapter_sweep',
            'unit': 'tokens/s',
            'platform': jax.default_backend(),
            'adapter_fleet': fleet,
            'adapter_rank': rank,
            'base_only': base_rec,
            'fleet_zipf': fleet_rec,
            'tokens_per_sec_ratio': round(
                fleet_rec['tokens_per_sec']
                / base_rec['tokens_per_sec'], 3)
            if base_rec['tokens_per_sec'] else None,
            'adapters': st['adapters'],
            'sampled_tokens': st['counts'].get('sampled_tokens', 0),
            'retraced_programs': retraced,
        }],
    }
    try:
        from mxnet_tpu import observability
        payload['telemetry'] = observability.summary()
    except Exception as e:
        payload['telemetry'] = {'enabled': False,
                                'error': '%s: %s'
                                % (type(e).__name__, e)}
    if retraced:
        raise AssertionError(
            'adapter/sampling rotation retraced compiled programs '
            'after warmup: %r' % (retraced,))
    if st['adapters']['resident'] < fleet:
        raise AssertionError(
            '%d-adapter fleet served but only %d resident'
            % (fleet, st['adapters']['resident']))
    return payload


def run(status, args):
    from mxnet_tpu import serving

    max_batch = 8 if args.quick else 32
    frozen = _build_frozen(max_batch)
    frozen.warmup()        # compile the ladder outside the timed windows
    session = serving.InferenceSession(
        frozen, deadline_ms=args.deadline_ms, watchdog=False)
    seconds = 0.5 if args.quick else 3.0
    sweep = []
    try:
        for bucket in frozen.policy.buckets:
            rec = bench_bucket(session, bucket, seconds, args.clients)
            print('bucket %3d: %s req/s, p50 %s ms, p99 %s ms, bulk '
                  '%s ex/s' % (bucket, rec['requests_per_sec'],
                               rec['latency_p50_ms'],
                               rec['latency_p99_ms'],
                               rec['bulk_examples_per_sec']),
                  flush=True)
            sweep.append(rec)
    finally:
        session.close()

    recompiles = frozen.compile_count
    payload = {
        'metrics': [{
            'metric': 'serving_bucket_sweep',
            'unit': 'requests/s',
            'clients': args.clients,
            'deadline_ms': args.deadline_ms,
            'buckets': list(frozen.policy.buckets),
            'sweep': sweep,
            'recompile_count': recompiles,
            'recompile_bound': len(frozen.policy.buckets),
            'recompiles_bounded': recompiles
            <= len(frozen.policy.buckets),
        }],
    }
    try:
        from mxnet_tpu import observability
        payload['telemetry'] = observability.summary()
    except Exception as e:    # telemetry must never sink the artifact
        payload['telemetry'] = {'enabled': False,
                                'error': '%s: %s'
                                % (type(e).__name__, e)}
    if not payload['metrics'][0]['recompiles_bounded']:
        raise AssertionError(
            '%d programs compiled for a %d-bucket ladder'
            % (recompiles, len(frozen.policy.buckets)))
    return payload


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--out', default='BENCH_SERVING.json')
    p.add_argument('--quick', action='store_true',
                   help='CI-sized sweep (small ladder, short windows)')
    p.add_argument('--decode', action='store_true',
                   help='generation sweep: continuous vs flush '
                        'batching (tokens/s, TTFT, per-token latency)')
    p.add_argument('--paged', action='store_true',
                   help='paged-KV-cache sweep: max concurrent '
                        'sequences at equal HBM budget (slot vs '
                        'paged), shared-prefix TTFT A/B, and the '
                        'speculative-decoding tokens/s + acceptance-'
                        'rate leg')
    p.add_argument('--adapters', action='store_true',
                   help='multi-adapter sweep: Zipf rotation over an '
                        '8-LoRA fleet (half sampled) at zero '
                        'retraces, adapter-vs-base tokens/s A/B')
    p.add_argument('--clients', type=int, default=4)
    p.add_argument('--deadline-ms', type=float, default=2.0)
    args = p.parse_args()

    from mxnet_tpu.resilience import run_instrument
    if args.adapters:
        fn, label = run_adapters, 'bench_adapters'
    elif args.paged:
        fn, label = run_paged, 'bench_paged_decode'
    elif args.decode:
        fn, label = run_decode, 'bench_decode'
    else:
        fn, label = run, 'bench_serving'
    return run_instrument(label, lambda status: fn(status, args),
                          out=args.out)


if __name__ == '__main__':
    sys.exit(main())
