#!/usr/bin/env python
"""Serving bench: closed-loop latency/throughput sweep over the
bucket ladder (docs/SERVING.md; CI stage 'bench-serving').

For every batch bucket the sweep drives the inference engine two
ways:

  * closed-loop single requests through the micro-batcher (one
    in-flight request per client, ``--clients`` concurrent clients)
    — measures request latency under batching: p50/p99, requests/s;
  * bulk batches of exactly the bucket size through the AOT program
    (``InferenceSession.infer_batch``) — measures the compiled
    program's examples/s ceiling per bucket.

Writes the standard instrument status JSON (mxnet_tpu.instrument.v2:
``status`` ok|degraded|unavailable, rc 0 on outage — the
BENCH_r05-proof contract every instrument in this repo honors) whose
payload carries per-bucket latency percentiles, requests/s, the
engine recompile count vs the ladder bound, and the telemetry summary
block.

Usage: python bench_serving.py [--quick] [--out BENCH_SERVING.json]
"""
import argparse
import sys
import threading
import time

sys.path.insert(0, '.')
import numpy as np  # noqa: E402

FEATURES = 64
CLASSES = 16


def _build_frozen(max_batch):
    import mxnet_tpu as mx
    from mxnet_tpu import serving
    np.random.seed(5)
    mx.random.seed(5)
    data = mx.sym.Variable('data')
    h = mx.sym.FullyConnected(data, num_hidden=128, name='fc1')
    h = mx.sym.Activation(h, act_type='relu')
    h = mx.sym.FullyConnected(h, num_hidden=128, name='fc2')
    h = mx.sym.Activation(h, act_type='relu')
    h = mx.sym.FullyConnected(h, num_hidden=CLASSES, name='fc3')
    out = mx.sym.SoftmaxOutput(h, name='softmax')
    mod = mx.mod.Module(out, context=mx.context.current_context())
    rs = np.random.RandomState(0)
    x = rs.randn(64, FEATURES).astype('float32')
    y = rs.randint(0, CLASSES, (64,)).astype('float32')
    it = mx.io.NDArrayIter(x, y, batch_size=32)
    mod.fit(it, num_epoch=1, optimizer_params=(('learning_rate', 0.1),))
    return serving.freeze(mod, max_batch=max_batch,
                          name='bench-serving')


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def bench_bucket(session, bucket, seconds, clients):
    """Closed-loop clients + bulk-batch throughput for one bucket."""
    rs = np.random.RandomState(bucket)
    x1 = rs.randn(FEATURES).astype('float32')
    xb = rs.randn(bucket, FEATURES).astype('float32')
    session.infer_batch([xb])          # compile outside the window

    latencies = []
    lock = threading.Lock()
    stop = time.perf_counter() + seconds

    def client():
        mine = []
        while time.perf_counter() < stop:
            t0 = time.perf_counter()
            session.infer(x1, timeout=30)
            mine.append(time.perf_counter() - t0)
        with lock:
            latencies.extend(mine)

    threads = [threading.Thread(target=client)
               for _ in range(clients)]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(seconds + 30)
    wall = time.perf_counter() - t_start

    # bulk path: examples/s of the padded compiled program
    reps = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        session.infer_batch([xb])
        reps += 1
    bulk_dt = time.perf_counter() - t0

    latencies.sort()
    return {
        'bucket': bucket,
        'requests': len(latencies),
        'requests_per_sec': round(len(latencies) / wall, 2)
        if wall else None,
        'latency_p50_ms': round(1e3 * _percentile(latencies, 0.50), 3)
        if latencies else None,
        'latency_p99_ms': round(1e3 * _percentile(latencies, 0.99), 3)
        if latencies else None,
        'bulk_examples_per_sec': round(reps * bucket / bulk_dt, 1)
        if bulk_dt else None,
    }


def run(status, args):
    from mxnet_tpu import serving

    max_batch = 8 if args.quick else 32
    frozen = _build_frozen(max_batch)
    frozen.warmup()        # compile the ladder outside the timed windows
    session = serving.InferenceSession(
        frozen, deadline_ms=args.deadline_ms, watchdog=False)
    seconds = 0.5 if args.quick else 3.0
    sweep = []
    try:
        for bucket in frozen.policy.buckets:
            rec = bench_bucket(session, bucket, seconds, args.clients)
            print('bucket %3d: %s req/s, p50 %s ms, p99 %s ms, bulk '
                  '%s ex/s' % (bucket, rec['requests_per_sec'],
                               rec['latency_p50_ms'],
                               rec['latency_p99_ms'],
                               rec['bulk_examples_per_sec']),
                  flush=True)
            sweep.append(rec)
    finally:
        session.close()

    recompiles = frozen.compile_count
    payload = {
        'metrics': [{
            'metric': 'serving_bucket_sweep',
            'unit': 'requests/s',
            'clients': args.clients,
            'deadline_ms': args.deadline_ms,
            'buckets': list(frozen.policy.buckets),
            'sweep': sweep,
            'recompile_count': recompiles,
            'recompile_bound': len(frozen.policy.buckets),
            'recompiles_bounded': recompiles
            <= len(frozen.policy.buckets),
        }],
    }
    try:
        from mxnet_tpu import observability
        payload['telemetry'] = observability.summary()
    except Exception as e:    # telemetry must never sink the artifact
        payload['telemetry'] = {'enabled': False,
                                'error': '%s: %s'
                                % (type(e).__name__, e)}
    if not payload['metrics'][0]['recompiles_bounded']:
        raise AssertionError(
            '%d programs compiled for a %d-bucket ladder'
            % (recompiles, len(frozen.policy.buckets)))
    return payload


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--out', default='BENCH_SERVING.json')
    p.add_argument('--quick', action='store_true',
                   help='CI-sized sweep (small ladder, short windows)')
    p.add_argument('--clients', type=int, default=4)
    p.add_argument('--deadline-ms', type=float, default=2.0)
    args = p.parse_args()

    from mxnet_tpu.resilience import run_instrument
    return run_instrument('bench_serving',
                          lambda status: run(status, args),
                          out=args.out)


if __name__ == '__main__':
    sys.exit(main())
